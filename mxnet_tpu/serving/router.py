"""Multi-engine serving front door with a fleet-wide observability plane.

``ServingRouter`` fronts N :class:`~.engine.ServingEngine` seats — the
"one engine per chip, one front door" scale-out shape — and routes
each request to the routable engine with the fewest router-observed
outstanding requests (least-outstanding, the standard L7 balancing
policy for long-tailed request costs). Seats come in two kinds:

- **in-process** engines, registered by handle (``add_engine(id,
  engine)``) and dispatched via ``engine.submit`` directly;
- **remote** engines, registered by the base URL of their
  ``engine.expose()`` endpoint, with per-engine health/stats/metrics/
  traces scraped off that endpoint. Dispatch prefers the BINARY WIRE
  (:mod:`.wire`): when the engine's ``/healthz`` advertises a
  ``wire_port``, the seat keeps a small pool of persistent
  multiplexed connections whose single reader thread per connection
  demuxes replies by correlation id — zero connections, threads or
  ``tokens.tolist()`` round-trips per request. A peer with no wire
  port (an old engine, or ``MXNET_TPU_WIRE=0``) — or a seat whose
  wire connections are momentarily down — falls back to the
  ``POST /submit`` HTTP/JSON long-poll, now driven by a BOUNDED
  per-seat waiter pool instead of a thread per in-flight request.

The observability plane is the point:

1. **Engine-labeled metrics** — every serving family carries an
   ``engine_id`` label (see :mod:`.metrics`); the router's own
   ``/metrics`` serves an AGGREGATED exposition: the local process
   registry unioned with every remote engine's scrape
   (:func:`~mxnet_tpu.telemetry.expo.merge_prometheus_texts`), so one
   Prometheus target sees the whole fleet.
2. **Cross-engine trace aggregation** — ``submit`` opens a
   ``router/request`` root span and propagates ``(trace_id,
   span_id)`` to the chosen engine (directly in-process, as dispatch
   payload fields for remote seats — the same frame-carried crossing
   the dist_async wire uses), so the engine-side
   ``serving/request → queue → pack → forward → complete`` tree
   parents under the router root across processes. The router's
   ``/traces`` and ``/traces/<id>`` merge the per-engine tail-sampled
   rings into one fleet view / one span tree, each span tagged with
   the engine that served it.
3. **Per-engine health scoreboard** — a poll thread folds engine
   heartbeats (``running``/``/healthz``, queue depth, worker-beat
   age, p95, qps) into per-engine gauges and a scoreboard dict; a
   stalled or unreachable engine is marked unroutable (new traffic
   avoids it; its failed dispatches re-queue to siblings), every
   transition emits a ``router_engine_state`` event, and a watchdog
   probe plus a ``router_scoreboard.json`` flight-recorder bundle
   section make a wedged engine self-diagnosing.

4. **Warm restarts** — the poll thread collects each engine's
   visited-shape **warmup manifest** (``/warmup`` /
   ``warmup_manifest()``), keeps the fleet union, and persists it at
   ``MXNET_TPU_WARMUP_MANIFEST`` whenever it grows; a replacement
   engine started with ``warmup(manifest=router.warmup_manifest())``
   (plus the persistent compilation cache,
   :mod:`mxnet_tpu.compile_cache`) replays the fleet's working set
   before ``add_engine`` admits it traffic — rolling restarts serve
   their first real request warm. ``remove_engine`` completes the
   drill.

5. **Fleet cost accounting** — ``/costs`` merges every engine's
   per-bucket cost ledger (device/compile seconds, requests, valid
   tokens; :class:`~.metrics.CostLedger`) into one fleet table with
   per-request / per-1k-token rates, and completed requests carry
   their engine-computed amortized ``future.cost`` through the router
   untouched.

6. **Fleet objectives** — the router runs its own SLO engine
   (:mod:`mxnet_tpu.telemetry.slo` / :mod:`~mxnet_tpu.telemetry.
   alerts`, gate ``MXNET_TPU_SLO``): availability ACROSS failover
   (router outcome counters — a failed-over request that completed on
   a sibling burns no budget), the fleet latency quantile over the
   router-observed end-to-end histogram (with trace-id exemplars on
   slow requests), and the routable-engine fraction off the
   scoreboard. ``/slo`` and ``/alerts`` serve the fleet view: the
   router's own objectives plus every seat's seat-level snapshot
   (local handles read directly, remote seats are scraped), so one
   endpoint answers both "is the fleet healthy" and "which engine is
   burning its budget".

Failover: a dispatch that dies of an ENGINE-SHAPED failure (engine
stopped, queue full, remote transport error) re-queues the request at
the front of the line for a sibling — requests are only lost to
explicit sheds (:class:`NoEngineAvailableError` when every candidate
is down/tried) or their own deadlines, never silently. Model errors
and deadline misses propagate to the caller untouched: retrying a
deterministic failure on every engine would just multiply it.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict, deque
from urllib.parse import urlsplit

import numpy as np

from .. import compile_cache, envvars
from ..retrying import Reconnector
from ..telemetry import attribution as _attribution
from ..telemetry import events as _events
from ..telemetry import incidents as _incidents
from ..telemetry import profiling as _profiling
from ..telemetry import recorder as _recorder
from ..telemetry import spans as _spans
from ..telemetry.registry import REGISTRY as _REGISTRY
from ..telemetry.trace import new_trace_id
from . import tenancy
from .engine import _SUBMIT_ERROR_STATUS, ServingEngine
from .metrics import (DispatchOverhead, LatencySummary, exemplar_gate,
                      merge_cost_buckets, slow_exemplar,
                      wire_bytes_counter, wire_fallback_counter)
from .queue import (DeadlineExceededError, EngineStoppedError,
                    InferenceFuture, QueueFullError, ServingError,
                    UnknownModelError, validate_sampling,
                    validate_tokens)
from .wire import WireClient, WireError

__all__ = ["ServingRouter", "NoEngineAvailableError", "RemoteEngineError"]

_router_seq = itertools.count()
_seat_seq = itertools.count()

# SLO-aware routing-weight hysteresis: a seat enters the DEGRADED
# state (weight tracks its health target) when the target falls to
# _W_ENTER, and returns to full weight only after _W_OK_POLLS
# consecutive polls with the target back above _W_EXIT — weights shed
# smoothly and never flap on a noisy boundary signal.
_W_ENTER = 0.7
_W_EXIT = 0.95
_W_OK_POLLS = 3


class NoEngineAvailableError(ServingError):
    """Shed: no routable engine (fleet down, or failover exhausted
    every candidate for this request)."""


class RemoteEngineError(ServingError):
    """A remote engine endpoint failed at the transport level
    (unreachable, timeout, non-JSON reply)."""


# engine-shaped failures: the request did not fail, the ENGINE did —
# eligible for failover to a sibling
_FAILOVER_ERRORS = (EngineStoppedError, QueueFullError, RemoteEngineError)

# remote /submit error_type -> local exception class (anything unknown
# lands on ServingError so callers still catch the serving taxonomy)
_ERROR_CLASSES = {
    "QueueFullError": QueueFullError,
    "DeadlineExceededError": DeadlineExceededError,
    "EngineStoppedError": EngineStoppedError,
    "UnknownModelError": UnknownModelError,
}


class RouterRequest:
    """One admitted request and its router-side breadcrumbs: the
    minted trace id, the ``router/request`` root span every engine-side
    span ultimately parents under, the engines already tried (failover
    must not ping-pong), and the absolute deadline (failover burns
    wall-clock; the remaining budget shrinks with each attempt)."""

    __slots__ = ("tokens", "token_types", "deadline", "future",
                 "trace_id", "span", "t_submit", "tried", "engine_id",
                 "requeues", "cid", "adopted", "decode", "stream",
                 "parts_seen", "relay_lock", "model_id", "tenant",
                 "tenant_class", "stages", "t_activity")

    def __init__(self, tokens, token_types=None, deadline_ms=None,
                 decode=None, stream=False, model_id=None, tenant=None,
                 tenant_class=None):
        self.tokens, self.token_types = validate_tokens(tokens,
                                                        token_types)
        # tenancy attribution: validated HERE (an unknown class is a
        # ValueError before any counter/journal), carried verbatim on
        # every dispatch payload + the HA journal entry so the serving
        # seat — first pick, failover sibling, peer adoption — bills
        # and WFQ-classes the request identically
        self.model_id = str(model_id) if model_id is not None else None
        self.tenant = str(tenant) if tenant is not None else None
        self.tenant_class = tenancy.normalize_class(tenant_class)
        self.trace_id = new_trace_id("req")
        self.t_submit = time.monotonic()
        self.deadline = (self.t_submit + deadline_ms / 1e3
                         if deadline_ms is not None else None)
        self.span = _spans.start_span(
            "router/request", trace_id=self.trace_id,
            attrs={"tokens": int(self.tokens.size)}, local_root=True)
        self.future = InferenceFuture()
        self.future.trace_id = self.trace_id
        # tried holds seat GENERATION tokens, not engine ids: a
        # replacement seat registered under a reused id is a FRESH
        # failover candidate, not forever poisoned by its predecessor
        self.tried = set()
        self.engine_id = None
        self.requeues = 0
        # HA correlation id: client-provided (resubmit dedupe across
        # routers) or minted from the trace id when journaling
        self.cid = None
        self.adopted = False
        # decode pass-through: generation params riding the dispatch
        # payload unchanged, and the streamed-parts relay state.
        # parts_seen is the next part index the CLIENT has not yet
        # seen: a failover re-run of a (deterministic) decode request
        # replays indices the client already has — the relay drops
        # them, so a killed connection mid-stream loses and duplicates
        # NOTHING
        self.decode = dict(decode) if decode else None
        self.stream = bool(stream)
        self.parts_seen = 0
        self.relay_lock = threading.Lock()
        # router-side stage stamps (dispatch transit, HA-journal ack):
        # the ENGINE's decomposition rides the reply; these feed the
        # router's own /whyslow aggregator
        self.stages = [] if _attribution.enabled() else None
        self.t_activity = None

    def remaining_ms(self, now=None):
        if self.deadline is None:
            return None
        return (self.deadline - (now if now is not None
                                 else time.monotonic())) * 1e3

    def relay_part(self, index, token):
        """Deliver one streamed token to the caller's future, deduped
        by part index (see ``parts_seen`` above). Seats call this from
        their transport threads; a request rides one transport at a
        time, but a FAILOVER's first relays can race a late in-flight
        partial from the old transport's reader — the lock makes the
        dedupe check-and-push atomic so no index delivers twice."""
        if index is None:
            return
        index = int(index)
        with self.relay_lock:
            if index < self.parts_seen:
                return
            self.parts_seen = index + 1
            self.future.push_part({"index": index, "token": token,
                                   "final": False})

    def expired(self, now=None):
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                > self.deadline)


class _FallbackPool:
    """Bounded waiter pool for the HTTP/JSON fallback dispatch path.

    The legacy shape spawned one unbounded daemon thread per in-flight
    remote request — a load spike against a slow engine thread-bombed
    the router. Jobs queue here instead; at most
    ``MXNET_TPU_WIRE_HTTP_POOL`` waiters per seat run them, spawned
    lazily only when every existing waiter is busy. ``close()`` lets
    the waiters drain what's queued and exit."""

    def __init__(self, name, size):
        self._name = str(name)
        self._size = max(1, int(size))
        self._dq = deque()
        self._cv = threading.Condition()
        self._threads = 0
        self._idle = 0
        self._closed = False
        self._seq = itertools.count()

    def submit(self, fn):
        """Queue one job; False when the pool is closed (the seat is
        being torn down — the caller resolves the request itself)."""
        with self._cv:
            if self._closed:
                return False
            self._dq.append(fn)
            if self._idle == 0 and self._threads < self._size:
                self._threads += 1
                threading.Thread(
                    target=self._run, daemon=True,
                    name=f"mxnet_tpu_router_http_{self._name}"
                         f"_{next(self._seq)}").start()
            else:
                self._cv.notify()
        return True

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def _run(self):
        while True:
            with self._cv:
                while not self._dq and not self._closed:
                    self._idle += 1
                    self._cv.wait(0.5)
                    self._idle -= 1
                if not self._dq:
                    self._threads -= 1
                    return          # closed and drained
                fn = self._dq.popleft()
            try:
                fn()
            except Exception as e:
                # a job resolves its own request via done(); an escape
                # here is a bug worth a trace, never a dead waiter pool
                _events.emit("router_http_pool_error",
                             pool=self._name, error=repr(e))


class _Seat:
    """One engine behind the router: routing state + scoreboard row."""

    kind = "?"

    def __init__(self, engine_id):
        self.engine_id = str(engine_id)
        # generation token: unique per seat OBJECT, so failover
        # bookkeeping survives a replacement under a reused id
        self.token = f"{self.engine_id}#{next(_seat_seq)}"
        self.outstanding = 0        # router-observed in flight
        self.dispatched = 0
        self.up = True              # optimistic until the first poll
        self.routable = True
        self.closed = False         # removed from the fleet
        self.consecutive_failures = 0
        self.last_change = time.time()
        self.queue_depth = None
        self.p95_ms = None
        self.qps = 0.0
        # hosted models (model_id -> version) learned off the health
        # poll; None = unknown (an old peer that advertises nothing) —
        # treated as hosting anything so mixed fleets keep routing
        self.models = None
        self.last_error = None
        self.last_picked = 0        # round-robin tie-break stamp
        self._prev_completed = None
        self._prev_poll = None
        self._manifest_count = None  # visited shapes at last collect
        # SLO-aware routing weight: 1.0 = full share; a seat burning
        # its error budget / drifting on cost / slow to canaries sheds
        # weight smoothly (poll-thread owned, dispatcher read-only)
        self.weight = 1.0
        self.hys = "healthy"        # healthy | degraded (hysteresis)
        self.ok_polls = 0
        self.burn = None            # last short-window burn rate
        self.cost_rate = None       # EMA windowed device_s/1k tokens
        self._prev_cost = None      # (request_s, valid_tokens)
        self._cost_age = 0          # polls since the EMA last updated
        self._sig_tick = 0          # throttles remote /slo fetches

    def cost_table(self):
        return None

    def row(self):
        return {"kind": self.kind, "up": self.up,
                "routable": self.routable,
                "outstanding": self.outstanding,
                "dispatched": self.dispatched,
                "queue_depth": self.queue_depth,
                "p95_ms": self.p95_ms, "qps": self.qps,
                "models": self.models,
                "weight": round(self.weight, 3),
                "burn": (round(self.burn, 3)
                         if self.burn is not None else None),
                "cost_rate": (round(self.cost_rate, 6)
                              if self.cost_rate is not None else None),
                "manifest_shapes": self._manifest_count,
                "consecutive_failures": self.consecutive_failures,
                "last_change": round(self.last_change, 3),
                "last_error": self.last_error}

    def hosts(self, model_id):
        """True when this seat can serve ``model_id`` (None names the
        seat's default model; a seat whose hosted set is unknown — an
        old peer — routes optimistically and 404s would fail over)."""
        return (model_id is None or self.models is None
                or model_id in self.models)

    def warmup_manifest(self):
        return None

    def slo_snapshot(self):
        """This seat's /slo body (None when the engine has no SLO
        evaluator — MXNET_TPU_SLO=0, or an old peer)."""
        return None

    def alerts_snapshot(self):
        return None

    def whyslow(self):
        """This seat's /whyslow body (None when the engine has no
        stage attribution — MXNET_TPU_ATTRIBUTION=0, or an old
        peer)."""
        return None

    def capture_summary(self):
        """This seat's /capture body (None when the engine has no
        capture store — MXNET_TPU_CAPTURE=0, or an old peer)."""
        return None

    def maintain(self):
        """Poll-thread housekeeping (wire connection upkeep)."""

    def close(self):
        """Release seat-owned transport resources (router stop /
        ``remove_engine``). Sets ``closed`` so a dispatch (or a poll
        ``maintain``) racing the removal fails over instead of driving
        a dead seat — subclasses must call ``super().close()``."""
        self.closed = True


class _LocalSeat(_Seat):
    kind = "local"

    def __init__(self, engine_id, engine):
        super().__init__(engine_id)
        self._engine = engine

    def dispatch(self, req, timeout_s, done):
        if self.closed:
            # picked just as remove_engine() raced in: engine-shaped —
            # the failover requeue hands the request to a sibling
            done(self, req, EngineStoppedError(
                f"engine {self.engine_id} seat was removed"), None)
            return
        submit_payload = getattr(self._engine, "submit_payload", None)
        if submit_payload is not None and (req.decode or req.stream):
            # decode engine: generation params + streaming ride the
            # payload dict (the same shape the wire/HTTP dispatch uses)
            fut, _streamed = submit_payload(dict(
                req.decode or {}, tokens=req.tokens,
                deadline_ms=req.remaining_ms(), stream=req.stream,
                trace_id=req.trace_id, span_id=req.span.span_id,
                model_id=req.model_id, tenant=req.tenant,
                tenant_class=req.tenant_class))
        else:
            fut = self._engine.submit(req.tokens, req.token_types,
                                      deadline_ms=req.remaining_ms(),
                                      trace_id=req.trace_id,
                                      parent_span_id=req.span.span_id,
                                      model_id=req.model_id,
                                      tenant=req.tenant,
                                      tenant_class=req.tenant_class)
        if req.stream:
            fut.add_part_callback(
                lambda _f, part: req.relay_part(part.get("index"),
                                                part.get("token")))

        def _cb(f):
            exc = f.exception(timeout=0)
            done(self, req, exc,
                 None if exc is not None else f.result(timeout=0),
                 cost=f.cost,
                 breakdown=getattr(f, "breakdown", None))

        fut.add_done_callback(_cb)

    def health(self):
        snap = self._engine.snapshot()
        return bool(snap.get("running")), snap

    def warmup_manifest(self):
        try:
            return self._engine.warmup_manifest()
        except Exception:
            return None

    def cost_table(self):
        try:
            return self._engine.cost_table()
        except Exception:
            return None

    def slo_snapshot(self):
        try:
            if self._engine.alerts is None:
                return None
            return self._engine.slo_snapshot()
        except Exception:
            return None

    def alerts_snapshot(self):
        try:
            if self._engine.alerts is None:
                return None
            return self._engine.alerts_snapshot()
        except Exception:
            return None

    def whyslow(self):
        try:
            return self._engine.whyslow()
        except Exception:
            return None

    def capture_summary(self):
        try:
            return self._engine.capture_summary()
        except Exception:
            return None


class _RemoteSeat(_Seat):
    kind = "remote"

    def __init__(self, engine_id, base_url, http_timeout_s=5.0,
                 overhead=None, wire_enabled=None, client_id=None):
        super().__init__(engine_id)
        self.base_url = base_url.rstrip("/")
        self._timeout = http_timeout_s
        self._last_costs = None     # last fetched /costs (see cost_table)
        self._overhead = overhead   # router-shared DispatchOverhead
        self._wire_enabled = (bool(wire_enabled) if wire_enabled
                              is not None
                              else bool(envvars.get("MXNET_TPU_WIRE")))
        self._client_id = str(client_id or f"router-{os.getpid():x}")
        self._wire = None           # WireClient once a port is known
        self._wire_peer = None      # engine id the pool was built for
        self._advertised = (None, None)   # (wire_port, engine_id) @ poll
        self._pool = _FallbackPool(
            self.engine_id, envvars.get("MXNET_TPU_WIRE_HTTP_POOL"))
        byt = wire_bytes_counter()
        self._b_out_json = byt.labels(side="router", transport="json",
                                      direction="out")
        self._b_in_json = byt.labels(side="router", transport="json",
                                     direction="in")
        self._c_fallback = wire_fallback_counter() \
            .labels(engine_id=self.engine_id)

    def _get(self, path, timeout=None):
        with urllib.request.urlopen(
                self.base_url + path,
                timeout=timeout if timeout is not None
                else self._timeout) as r:
            return r.read().decode()

    def row(self):
        out = super().row()
        wire = self._wire
        out["transport"] = ("wire" if wire is not None
                            and wire.has_live() else "json")
        out["wire_port"] = self._advertised[0]
        return out

    # -- binary wire path ---------------------------------------------------
    def maintain(self):
        """Poll-thread housekeeping for the wire transport: (re)open
        persistent connections toward the advertised dispatch port,
        time out unanswered in-flight requests. All blocking connect/
        handshake work lives HERE — the dispatch path only ever queues
        frames on already-live connections."""
        if not self._wire_enabled or self.closed:
            # a poll racing remove_engine() must not resurrect the
            # closed seat's wire pool (a pure leak: the seat can never
            # be picked again)
            return
        port, peer_eid = self._advertised
        wire = self._wire
        if wire is not None and (
                port is None or wire.port != int(port)
                or (peer_eid is not None
                    and self._wire_peer not in (None, peer_eid))):
            # peer downgraded (restarted with MXNET_TPU_WIRE=0), came
            # back on a different port, or a REPLACEMENT engine took
            # the same port under a new id (the old client would pin
            # a stale expect and refuse it forever): rebuild the pool
            self._wire = None
            wire.close()
            wire = None
        if port is None:
            return
        if wire is None:
            host = urlsplit(self.base_url).hostname or "127.0.0.1"
            wire = WireClient(host, int(port),
                              client_id=self._client_id,
                              expect_engine_id=peer_eid)
            self._wire = wire
            self._wire_peer = peer_eid
        wire.ensure()
        wire.sweep()

    def _dispatch_wire(self, wire, req, timeout_s, done):
        # raw typed ndarrays — no tolist()/JSON round trip; trace and
        # span ids ride the frame so the engine-side span tree parents
        # under the router root exactly as it did over HTTP
        payload = {"tokens": req.tokens,
                   "token_types": req.token_types,
                   "deadline_ms": req.remaining_ms(),
                   "trace_id": req.trace_id,
                   "span_id": req.span.span_id,
                   "model_id": req.model_id,
                   "tenant": req.tenant,
                   "tenant_class": req.tenant_class}
        if req.decode:
            payload.update(req.decode)
        if req.stream:
            payload["stream"] = True
        t0 = time.perf_counter()
        t0m = time.monotonic()

        def _on_part(body):
            req.relay_part(body.get("seq"), body.get("token"))

        def _on_wire(exc, body):
            rt_ms = (time.perf_counter() - t0) * 1e3
            if exc is not None:
                # connection died or reply timed out: engine-shaped —
                # the router's failover requeues the request
                done(self, req, RemoteEngineError(
                    f"engine {self.engine_id} wire dispatch failed: "
                    f"{exc}"), None)
                return
            err_type = body.get("error_type")
            if err_type is None:
                engine_ms = body.get("engine_ms")
                if self._overhead is not None and engine_ms is not None:
                    self._overhead.observe("wire",
                                           rt_ms - float(engine_ms))
                # dispatch transit: the whole round trip as one span —
                # the engine's own stage/* children start later, so the
                # innermost-wins extractor bills them their slices and
                # the remainder (serialize + queue + socket) to
                # ``dispatch``
                _attribution.stamp(
                    req, "dispatch", t0m, time.monotonic(),
                    attrs={"transport": "wire",
                           "engine_id": self.engine_id,
                           "engine_ms": engine_ms})
                done(self, req, None, np.asarray(body.get("result")),
                     cost=body.get("cost"),
                     breakdown=body.get("breakdown"))
                return
            if err_type == "WireError":
                # protocol-level refusal from the listener (bad frame
                # shape we somehow sent): transport-shaped
                exc2 = RemoteEngineError(
                    body.get("error")
                    or f"engine {self.engine_id} wire error")
            else:
                cls = _ERROR_CLASSES.get(err_type, ServingError)
                exc2 = cls(body.get("error")
                           or f"engine {self.engine_id} error")
            done(self, req, exc2, None)

        wire.dispatch(payload, _on_wire, timeout_s,
                      on_part=_on_part if req.stream else None)

    # -- dispatch (wire preferred, bounded HTTP/JSON fallback) --------------
    def dispatch(self, req, timeout_s, done):
        if self.closed:
            # removal raced the pick: fail over immediately instead of
            # paying an HTTP timeout against a seat already torn down
            done(self, req, RemoteEngineError(
                f"engine {self.engine_id} seat was removed"), None)
            return
        wire = self._wire
        if wire is not None:
            try:
                self._dispatch_wire(wire, req, timeout_s, done)
                return
            except WireError:
                pass    # no live connection right now: HTTP still works
        if self._wire_enabled:
            # a wire-capable router dispatching over HTTP: the peer has
            # no wire port, or its connections are down — visible so an
            # operator can tell "fast path" from "limping"
            self._c_fallback.inc()
        payload = {"tokens": req.tokens.tolist(),
                   "token_types": (req.token_types.tolist()
                                   if req.token_types is not None
                                   else None),
                   "deadline_ms": req.remaining_ms(),
                   "trace_id": req.trace_id,
                   "span_id": req.span.span_id,
                   "model_id": req.model_id,
                   "tenant": req.tenant,
                   "tenant_class": req.tenant_class,
                   "timeout_s": timeout_s}
        if req.decode:
            payload.update(req.decode)
        if req.stream:
            payload["stream"] = True
        t0 = time.perf_counter()

        # the /submit long-poll blocks for the whole request; a BOUNDED
        # waiter pool keeps the router's dispatch loop free without the
        # legacy thread-per-in-flight-request bomb (in-process seats
        # resolve via callbacks)
        def _run():
            exc = value = cost = breakdown = None
            body = None
            t0m = time.monotonic()
            try:
                data = json.dumps(payload).encode()
                self._b_out_json.inc(len(data))
                http_req = urllib.request.Request(
                    self.base_url + "/submit", data=data,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(
                        http_req, timeout=timeout_s + self._timeout) as r:
                    if req.stream:
                        # chunked JSON lines: one per generated token,
                        # final body last (the decode engine's HTTP
                        # fallback for wire-less routers)
                        body = None
                        for line in r:
                            self._b_in_json.inc(len(line))
                            if not line.strip():
                                continue
                            part = json.loads(line.decode())
                            if part.get("final", True):
                                body = part
                                break
                            req.relay_part(part.get("seq"),
                                           part.get("token"))
                        if body is None:
                            raise RemoteEngineError(
                                f"engine {self.engine_id} stream ended "
                                "without a final body")
                    else:
                        raw = r.read()
                        self._b_in_json.inc(len(raw))
                        body = json.loads(raw.decode())
            except urllib.error.HTTPError as e:
                try:
                    body = json.loads(e.read().decode())
                except Exception:
                    exc = RemoteEngineError(
                        f"engine {self.engine_id}: HTTP {e.code}")
            except Exception as e:
                exc = RemoteEngineError(
                    f"engine {self.engine_id} unreachable: {e!r}")
            if exc is None:
                if body.get("ok"):
                    # decode results are token ids (the engine tags
                    # its reply, covering requests that rode engine
                    # defaults); the encoder path keeps its historical
                    # float JSON round trip
                    value = np.asarray(body["result"],
                                       np.int32 if (req.decode
                                                    or req.stream
                                                    or body.get(
                                                        "decode"))
                                       else np.float32)
                    cost = body.get("cost")
                    breakdown = body.get("breakdown")
                    engine_ms = body.get("engine_ms")
                    if self._overhead is not None \
                            and engine_ms is not None:
                        self._overhead.observe(
                            "json", (time.perf_counter() - t0) * 1e3
                            - float(engine_ms))
                    _attribution.stamp(
                        req, "dispatch", t0m, time.monotonic(),
                        attrs={"transport": "json",
                               "engine_id": self.engine_id,
                               "engine_ms": engine_ms})
                else:
                    cls = _ERROR_CLASSES.get(body.get("error_type"),
                                             ServingError)
                    exc = cls(body.get("error")
                              or f"engine {self.engine_id} error")
            done(self, req, exc, value, cost=cost, breakdown=breakdown)

        if not self._pool.submit(_run):
            done(self, req, RemoteEngineError(
                f"engine {self.engine_id} seat is closed"), None)

    def close(self):
        super().close()
        wire, self._wire = self._wire, None
        if wire is not None:
            wire.close()
        self._pool.close()

    def health(self):
        try:
            hz = json.loads(self._get("/healthz"))
            ok = bool(hz.get("ok"))
            # the advertised dispatch port (and the engine's REAL id —
            # the seat may be registered under an operator alias) feed
            # maintain()'s connection upkeep on this same poll thread
            self._advertised = (hz.get("wire_port"),
                                hz.get("engine_id"))
        except urllib.error.HTTPError as e:
            try:
                hz = json.loads(e.read().decode())
            except Exception:
                hz = {"error": f"HTTP {e.code}"}
            ok = False
        except Exception as e:
            return False, {"error": repr(e)}
        snap = {}
        if ok:
            try:
                snap = json.loads(self._get("/stats"))
            except Exception as e:
                return False, {"error": repr(e)}
        snap.setdefault("queue_depth", hz.get("queue_depth"))
        snap.setdefault("seconds_since_beat", hz.get("seconds_since_beat"))
        return ok, snap

    def metrics_text(self):
        return self._get("/metrics")

    def traces_summary(self):
        try:
            return json.loads(self._get("/traces"))
        except Exception:
            return None

    def get_trace(self, trace_id):
        from urllib.parse import quote
        try:
            return json.loads(
                self._get("/traces/" + quote(trace_id, safe="")))
        except Exception:
            return None

    def warmup_manifest(self):
        try:
            return json.loads(self._get("/warmup"))
        except Exception:
            return None

    def cost_table(self):
        # books are cumulative: a seat that stops answering (died,
        # restarting) must not DROP its billed history from the fleet
        # table, so the last fetched ledger stands in for it
        try:
            self._last_costs = json.loads(self._get("/costs"))
        except Exception:
            return self._last_costs
        return self._last_costs

    def slo_snapshot(self):
        # a 404 body ({"error": "no SLO evaluator"}) parses but is not
        # a snapshot: only objective-bearing replies count
        try:
            snap = json.loads(self._get("/slo"))
        except Exception:
            return None
        return snap if "objectives" in snap else None

    def alerts_snapshot(self):
        try:
            snap = json.loads(self._get("/alerts"))
        except Exception:
            return None
        return snap if "rules" in snap else None

    def incidents_snapshot(self):
        try:
            snap = json.loads(self._get("/incidents"))
        except Exception:
            return None
        return snap if "open" in snap else None

    def whyslow(self):
        # a 404 body ({"error": "no stage attribution"}) parses but is
        # not a snapshot: only stage-bearing replies count
        try:
            snap = json.loads(self._get("/whyslow"))
        except Exception:
            return None
        return snap if "stages" in snap else None

    def capture_summary(self):
        # a 404 body ({"error": "traffic capture disabled"}) parses
        # but is not a summary: only record-bearing replies count
        try:
            snap = json.loads(self._get("/capture"))
        except Exception:
            return None
        return snap if "records_written" in snap else None


class ServingRouter:
    """Least-outstanding front door over N serving engines.

    Parameters
    ----------
    engines : optional initial fleet — a ``{engine_id: target}`` dict
        or an iterable of :class:`ServingEngine` (their own
        ``engine_id`` names the seat); a ``target`` is an engine
        handle (in-process) or an ``http://host:port`` exposition base
        URL (remote).
    max_queue_depth : router admission bound (like the engine's —
        backpressure, never unbounded growth).
    poll_interval_s : health-scoreboard poll period.
    health_fail_after : consecutive failed polls before an engine is
        marked down (dispatch-observed stop/transport errors mark it
        down immediately).
    dispatch_timeout_s : per-attempt cap a remote long-poll waits for
        one engine before the transport gives up.
    """

    COUNTERS = ("submitted", "completed", "failed", "expired",
                "cancelled", "requeued", "shed_queue_full",
                "shed_no_engine", "rejected_stopped", "adopted")

    def __init__(self, engines=None, max_queue_depth=1024,
                 poll_interval_s=1.0, health_fail_after=1,
                 default_deadline_ms=None, dispatch_timeout_s=600.0,
                 router_id=None, wire=None, peer=None):
        self.router_id = (str(router_id) if router_id is not None
                          else f"router-{os.getpid():x}-"
                               f"{next(_router_seq)}")
        # wire=None follows MXNET_TPU_WIRE; False pins every remote
        # seat to the HTTP/JSON path (the bench A/B and the fallback
        # regression test need a JSON-only router on demand)
        self._wire_flag = (bool(wire) if wire is not None
                           else bool(envvars.get("MXNET_TPU_WIRE")))
        # router-observed remote dispatch overhead (round trip minus
        # engine-observed wall) by transport — THE wire-vs-JSON number
        self.dispatch_overhead = DispatchOverhead()
        self._seats = OrderedDict()
        # cost ledgers of seats removed by remove_engine: the fleet
        # /costs books are cumulative, so a rolling-restart drill must
        # not drop the dead engine's billed requests from the table
        self._retired_costs = OrderedDict()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue = deque()
        self._max_queue_depth = int(max_queue_depth)
        self._poll_interval_s = float(poll_interval_s)
        self._fail_after = max(1, int(health_fail_after))
        self._default_deadline_ms = default_deadline_ms
        self._dispatch_timeout_s = float(dispatch_timeout_s)
        self._pending = 0           # admitted, not yet resolved
        self._closed = False
        self._abort = False
        self._started = False
        self._dispatcher = None
        self._poller = None
        self._stop_evt = threading.Event()
        self._expo = None
        self._probe_name = f"serving_router_{id(self):x}"
        # fleet SLO engine (MXNET_TPU_SLO): built in start(), serves
        # /slo + /alerts; exemplar gate shared with the engine via
        # metrics.exemplar_gate/slow_exemplar
        self._slo = None
        # memoized fleet top-stage attribution for alert payloads
        # (ts, rows) — see _whyslow_top
        self._whyslow_top_cache = None
        # black-box canary prober (MXNET_TPU_CANARY): built in
        # start(), probes every seat from outside over wire + HTTP and
        # feeds the per-seat canary-absence page rules
        self._canary = None
        # history scraper (MXNET_TPU_HISTORY): samples the fleet-merged
        # exposition into the retrospective store — built in start()
        self._history = None
        # shadow-diff mirror (MXNET_TPU_SHADOW): mirrors a fraction of
        # completed live traffic at a candidate seat and keeps the
        # /shadow verdict the swap gate consults — built in start();
        # None means no mirror branch in _on_done at all
        self._shadow = None
        self._exemplars = exemplar_gate()
        self._pick_seq = itertools.count(1)
        # SLO-aware routing weights (MXNET_TPU_ROUTER_WEIGHTS): the
        # poll thread folds per-seat burn rate, windowed cost drift
        # and canary latency into a smoothed weight the picker divides
        # outstanding load by — off, every weight stays 1.0 and the
        # pick order is exactly the classic least-outstanding
        self._weights_on = bool(envvars.get("MXNET_TPU_ROUTER_WEIGHTS"))
        self._w_floor = max(1e-3, float(
            envvars.get("MXNET_TPU_ROUTER_WEIGHT_FLOOR")))
        self._w_gain = min(1.0, max(0.01, float(
            envvars.get("MXNET_TPU_ROUTER_WEIGHT_GAIN"))))
        # a seat's burn signal costs a full SLO evaluation (an HTTP
        # /slo GET for remote seats, an evaluator tick+evaluate for
        # local handles): fetch it at most every ~2 s per seat
        # (reusing the last value in between) so default-on weights
        # don't multiply the poll thread's per-tick work
        self._slo_every = max(1, int(round(2.0 / max(
            0.05, float(poll_interval_s)))))
        self._g_weight = _REGISTRY.gauge(
            "mxnet_tpu_router_engine_weight",
            "SLO-aware routing weight per seat (1 = full share; a "
            "seat burning its error budget, drifting on cost or slow "
            "to canaries sheds smoothly)", ("engine_id",))
        # -- router active/active HA ------------------------------------
        # each admitted SUBMIT is journaled (cid + payload) to the
        # peer over the wire; when this router dies, the survivor
        # adopts the orphaned in-flight requests front-of-queue and a
        # client resubmitting the same cid attaches instead of
        # duplicating work
        self._peer_url = (str(peer).rstrip("/") if peer
                          else envvars.get("MXNET_TPU_ROUTER_HA_PEER"))
        if self._peer_url:
            self._peer_url = self._peer_url.rstrip("/")
        self._ha_on = bool(envvars.get("MXNET_TPU_ROUTER_HA"))
        self._ha = None             # inbound journal listener
        self._peer = None           # outbound WireClient to the peer
        self._peer_rid = None
        self._peer_ha_port = None
        self._peer_alive = None     # None unknown / True / False dead
        self._peer_fails = 0
        # backoff gate for the peer /healthz dial: a blackholed peer
        # must not cost the seat-health poll thread a full connect
        # timeout on EVERY tick (same policy the wire reconnects use)
        self._peer_recon = Reconnector()
        self._journal = OrderedDict()    # peer's in-flight: cid->entry
        self._journal_cap = int(envvars.get("MXNET_TPU_ROUTER_HA_JOURNAL"))
        self._ha_ack_s = float(envvars.get("MXNET_TPU_ROUTER_HA_ACK_S"))
        self._live_cids = OrderedDict()  # our in-flight cids -> future
        self._adopted = OrderedDict()    # adopted orphans: cid->future
        self._adopted_cap = 4096
        self._c_ha = None
        self._died = False
        if self._ha_on and self._peer_url:
            self._ha_setup()
        # trace -> engines that served it (bounded): lets the merged
        # /traces summary attribute LOCAL-engine traces too (remote
        # attribution comes from which ring a span was scraped off)
        self._trace_engines = OrderedDict()
        self._trace_engines_cap = 1024

        self._c = {name: 0 for name in self.COUNTERS}
        req_total = _REGISTRY.counter(
            "mxnet_tpu_router_requests_total",
            "router requests by admission/completion outcome", ("event",))
        self._reg_c = {name: req_total.labels(event=name)
                       for name in self.COUNTERS}
        self._c_dispatch = _REGISTRY.counter(
            "mxnet_tpu_router_dispatch_total",
            "requests dispatched, per engine", ("engine_id",))
        self._c_failover = _REGISTRY.counter(
            "mxnet_tpu_router_failover_total",
            "failover requeues, per FAILED engine", ("engine_id",))
        self._g_up = _REGISTRY.gauge(
            "mxnet_tpu_router_engine_up",
            "1 when the engine is routable, else 0", ("engine_id",))
        self._g_queue_depth = _REGISTRY.gauge(
            "mxnet_tpu_router_engine_queue_depth",
            "engine-reported admission-queue depth at last poll",
            ("engine_id",))
        self._g_inflight = _REGISTRY.gauge(
            "mxnet_tpu_router_engine_inflight",
            "router-observed in-flight requests, per engine",
            ("engine_id",))
        self._g_fleet = _REGISTRY.gauge(
            "mxnet_tpu_router_engines_up", "routable engines")
        self._c_scrape_err = _REGISTRY.counter(
            "mxnet_tpu_router_scrape_errors_total",
            "remote-engine scrape failures at the aggregated /metrics",
            ("engine_id",))
        # fleet-union warmup manifest: the poll thread folds every
        # live engine's visited-shape manifest in here and persists
        # the union at MXNET_TPU_WARMUP_MANIFEST so a restarting
        # engine can replay the fleet's working set (warm restart)
        self._fleet_manifest = None
        self._g_manifest = _REGISTRY.gauge(
            "mxnet_tpu_router_warmup_manifest_shapes",
            "shape buckets in the fleet-union warmup manifest")
        self.total_ms = LatencySummary(
            4096, _REGISTRY.histogram(
                "mxnet_tpu_router_latency_ms",
                "router-observed end-to-end latency", ("stage",))
            .labels(stage="total"))

        if engines:
            items = (engines.items() if isinstance(engines, dict)
                     else ((getattr(e, "engine_id", None), e)
                           for e in engines))
            for eid, target in items:
                self.add_engine(eid, target)

    # -- fleet membership --------------------------------------------------
    def add_engine(self, engine_id, target):
        """Register one engine seat: an in-process
        :class:`ServingEngine` handle, or the base URL string of a
        remote engine's ``expose()`` endpoint."""
        if isinstance(target, str):
            seat = _RemoteSeat(engine_id or target, target,
                               overhead=self.dispatch_overhead,
                               wire_enabled=self._wire_flag,
                               client_id=self.router_id)
        elif isinstance(target, ServingEngine) or hasattr(target, "submit"):
            seat = _LocalSeat(
                engine_id if engine_id is not None
                else getattr(target, "engine_id", None), target)
        else:
            raise TypeError(f"engine target {target!r} is neither a "
                            "ServingEngine nor an exposition URL")
        with self._lock:
            if seat.engine_id in self._seats:
                raise ValueError(
                    f"engine id {seat.engine_id!r} already registered")
            self._seats[seat.engine_id] = seat
            self._g_up.labels(engine_id=seat.engine_id).set(1)
            self._g_weight.labels(engine_id=seat.engine_id).set(1.0)
            self._g_inflight.labels(engine_id=seat.engine_id) \
                .set_function(lambda s=seat: s.outstanding)
        _events.emit("router_engine_added", router_id=self.router_id,
                     engine_id=seat.engine_id, kind=seat.kind)
        return self

    def remove_engine(self, engine_id):
        """Deregister one seat (the rolling-restart drill: remove the
        dead engine, then ``add_engine`` its warmed replacement under
        the same id). In-flight dispatches to it resolve through the
        normal failover path; new traffic stops immediately."""
        engine_id = str(engine_id)
        with self._lock:
            seat = self._seats.pop(engine_id, None)
            if seat is None:
                raise KeyError(f"engine id {engine_id!r} not registered")
            # closed is visible to a dispatcher that picked this seat
            # BEFORE the pop: its dispatch fails over immediately (and
            # the poll thread's maintain() stops touching the seat)
            # instead of erroring the request against a dead target
            seat.closed = True
            self._g_up.labels(engine_id=engine_id).set(0)
            self._g_weight.labels(engine_id=engine_id).set(0)
            self._g_inflight.labels(engine_id=engine_id).set(0)
            self._g_queue_depth.labels(engine_id=engine_id).set(0)
        # snapshot the departing seat's cumulative cost ledger OUTSIDE
        # the lock (remote seats scrape /costs) so the fleet books keep
        # every request it ever billed
        table = seat.cost_table()
        if table is not None:
            with self._lock:
                self._retired_costs[engine_id] = table
        # then drop its transport: closing the wire pool fails its
        # in-flight dispatches with WireError → failover requeues them
        # to siblings (the rolling-restart drill's zero-loss contract)
        seat.close()
        _events.emit("router_engine_removed", router_id=self.router_id,
                     engine_id=engine_id, kind=seat.kind)
        # release any incident hold on this seat: a seat that LEFT the
        # fleet must not pin an incident open forever (its replacement
        # starts up without a down→up transition) — same contract as
        # AlertDaemon.remove_rule's final resolved
        _events.emit("router_engine_state", router_id=self.router_id,
                     engine_id=engine_id, state="removed",
                     reason="remove_engine")
        return self

    def engine_ids(self):
        with self._lock:
            return list(self._seats)

    def engine_handle(self, engine_id):
        """The in-process engine behind a seat (None for remote seats
        or unknown ids) — the autoscaler uses it to stop a replaced
        incarnation it didn't spawn itself."""
        with self._lock:
            seat = self._seats.get(str(engine_id))
        return seat._engine if isinstance(seat, _LocalSeat) else None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        with self._lock:
            if self._started:
                return self
            if self._closed:
                raise EngineStoppedError("router cannot be restarted")
            if not self._seats:
                raise ValueError("router has no engines; add_engine first")
            self._started = True
            self._stop_evt.clear()
            self._dispatcher = threading.Thread(
                target=self._run_dispatch, daemon=True,
                name="mxnet_tpu_router_dispatch")
            self._poller = threading.Thread(
                target=self._run_poll, daemon=True,
                name="mxnet_tpu_router_health")
        # the router is a serving front door: it explains its own
        # death the same way an engine does (probe + bundle section),
        # and its bundle carries the FLEET scoreboard
        _recorder.install()
        _recorder.register_probe(self._probe_name, self._watchdog_probe)
        _recorder.add_bundle_section("router_scoreboard", self.snapshot)
        _profiling.ensure_started()
        _incidents.install()
        # fleet objectives: availability across failover, fleet
        # latency quantile, routable-engine fraction — judged by the
        # same burn-rate machinery every engine runs on itself
        if envvars.get("MXNET_TPU_SLO"):
            from ..telemetry.alerts import (AlertDaemon, default_burn_rules,
                                            default_router_objectives)
            from ..telemetry.slo import SloEvaluator
            evaluator = SloEvaluator(self.router_id)
            names = default_router_objectives(evaluator, self)
            self._slo = AlertDaemon(evaluator)
            # fleet "why slow" on the fleet page: the router's own
            # aggregator only sees dispatch/ha_ack, so a firing
            # fleet_latency payload attaches the MERGED top stages
            # (short TTL cache — /alerts renders every rule's payload
            # and must not re-scrape every seat per rule)
            self._slo.attribution_fn = self._whyslow_top
            default_burn_rules(self._slo, names)
            self._slo.start()
        # black-box monitoring: the canary prober serves the product
        # path from OUTSIDE each seat (wire + HTTP round-robined) and
        # declares the per-seat canary-absence page rule on the fleet
        # daemon — a wedged engine pages even with a green /healthz
        if envvars.get("MXNET_TPU_CANARY"):
            from ..telemetry.canary import CanaryProber
            self._canary = CanaryProber(self._canary_targets,
                                        owner_id=self.router_id,
                                        alerts=self._slo)
            self._canary.start()
        # retrospective history: the router's scraper samples the
        # fleet-MERGED exposition (this registry + every routable
        # remote seat), so one /query_range answers for the fleet
        if envvars.get("MXNET_TPU_HISTORY"):
            from ..telemetry.history import HistoryScraper
            self._history = HistoryScraper(
                self.router_id, text_fn=self.metrics_text,
                slo_fn=(self.slo_snapshot if self._slo is not None
                        else None),
                alerts_fn=(self.alerts_snapshot
                           if self._slo is not None else None)).start()
        # shadow-diff validation (MXNET_TPU_SHADOW): the mirror is
        # built DISARMED — set_shadow_target() arms it at a candidate.
        # Off (the default) this is one env read: no mirror branch in
        # the completion path, no mxnet_tpu_shadow_* families
        if envvars.get("MXNET_TPU_SHADOW"):
            from .shadow import ShadowMirror
            self._shadow = ShadowMirror(self.router_id)
        # chaos harness (MXNET_TPU_CHAOS): register as a fault target
        # (kill_router / kill_wire) — one env read when off
        if envvars.get("MXNET_TPU_CHAOS"):
            from .chaos import register_router as _chaos_register
            _chaos_register(self)
        self._poll_once()           # scoreboard fresh before traffic
        self._dispatcher.start()
        self._poller.start()
        _events.emit("router_start", router_id=self.router_id,
                     engines=self.engine_ids())
        return self

    def stop(self, drain=True, timeout=None):
        """Shut the router down (engines are NOT stopped — the router
        fronts them, it doesn't own them). ``drain=True`` waits for
        every admitted request to resolve; ``drain=False`` fails
        undispatched requests with :class:`EngineStoppedError`."""
        if self._died:
            return      # die() already tore everything down abruptly
        _events.emit("router_stop", router_id=self.router_id, drain=drain)
        with self._cond:
            already = self._closed
            self._closed = True
            if not drain:
                self._abort = True
            stranded = []
            if not drain:
                stranded = list(self._queue)
                self._queue.clear()
            self._cond.notify_all()
        for req in stranded:
            self._finish(req, EngineStoppedError(
                "router stopped before request was dispatched"),
                "cancelled")
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        timed_out = False
        if drain:
            with self._cond:
                while self._pending > 0:
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        timed_out = True
                        break
                    self._cond.wait(0.2 if remaining is None
                                    else min(0.2, remaining))
        self._stop_evt.set()
        for t in (self._dispatcher, self._poller):
            if t is not None:
                t.join(timeout=5.0)
        if not already:
            _recorder.unregister_probe(self._probe_name)
            _recorder.remove_bundle_section("router_scoreboard")
            if self._canary is not None:
                self._canary.stop()
            if self._slo is not None:
                self._slo.stop()
            if self._history is not None:
                self._history.stop()
            if self._shadow is not None:
                self._shadow.close()
        with self._lock:
            expo, self._expo = self._expo, None
            ha, self._ha = self._ha, None
            peer, self._peer = self._peer, None
            seats = list(self._seats.values())
        if expo is not None:
            expo.close()
        if ha is not None:
            ha.close()
        if peer is not None:
            peer.close()
        # transports are router-owned even though the engines aren't:
        # drop the persistent wire pools and HTTP waiter pools
        for seat in seats:
            seat.close()
        if timed_out:
            raise ServingError("router did not drain in time")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False

    @property
    def running(self):
        with self._lock:
            return (self._started and not self._closed
                    and self._dispatcher is not None
                    and self._dispatcher.is_alive())

    # -- client surface ----------------------------------------------------
    def submit(self, tokens, token_types=None, deadline_ms=None,
               cid=None, max_new_tokens=None, eos_id=None,
               stream=False, temperature=None, top_k=None, top_p=None,
               seed=None, model_id=None, tenant=None,
               tenant_class=None):
        """Admit one request; returns an :class:`InferenceFuture`
        whose ``trace_id`` names the request fleet-wide. Sheds loudly:
        :class:`QueueFullError` (router queue at bound),
        :class:`NoEngineAvailableError` (no routable engine),
        :class:`EngineStoppedError` (router not running).

        ``cid`` is the HA correlation id: a client resubmitting the
        same cid (after its first router died mid-request) ATTACHES to
        the already-adopted/live request instead of duplicating work.
        With an HA peer configured, every admitted request is
        journaled (cid + payload) to the peer before it becomes
        dispatchable, so a router death orphans nothing.

        ``max_new_tokens``/``eos_id``/``stream`` are the DECODE
        pass-through (seats fronting a :class:`~.decode.DecodeEngine`):
        generation params ride the dispatch payload unchanged, and
        with ``stream=True`` the returned future's :meth:`~.queue.
        InferenceFuture.stream` yields each generated token as the
        engine produces it — over the wire as partial RESULT frames,
        over HTTP as chunked JSON lines, in-process as direct part
        relays, deduped by index across failover.

        ``temperature``/``top_k``/``top_p``/``seed`` select seeded
        sampling on the serving seat (validated HERE, the typed
        :class:`~.queue.InvalidSamplingError` before any journaling or
        dispatch). A sampled request with no seed gets one MINTED at
        admission — the seed then rides the dispatch payload and the
        HA journal entry, so a failover re-dispatch (this router's
        retry or the peer's adoption) resamples the identical tokens
        and the stream dedupe stays byte-exact.

        ``model_id`` routes the request to a seat advertising that
        hosted model (None = each seat's default); ``tenant``/
        ``tenant_class`` attribute it to an owner and its WFQ
        admission class on the serving seat. All three ride every
        dispatch payload and the HA journal, so failover and peer
        adoption preserve the attribution."""
        if deadline_ms is None:
            deadline_ms = self._default_deadline_ms
        if cid is not None and self._c_ha is not None:
            existing = self._ha_lookup(str(cid))
            if existing is not None:
                return existing
        temperature, top_k, top_p, seed = validate_sampling(
            temperature, top_k, top_p, seed)
        decode = {}
        if max_new_tokens is not None:
            decode["max_new_tokens"] = int(max_new_tokens)
        if eos_id is not None:
            decode["eos_id"] = int(eos_id)
        if temperature is not None:
            decode["temperature"] = temperature
            if seed is None and temperature > 0:
                # mint the replay seed at the ROUTER so every
                # dispatch of this request — first try, retry on a
                # dead seat, HA-peer adoption — samples identically
                seed = int.from_bytes(os.urandom(4),
                                      "little") & 0x7FFFFFFF
        if top_k is not None:
            decode["top_k"] = top_k
        if top_p is not None:
            decode["top_p"] = top_p
        if seed is not None:
            decode["seed"] = seed
        # validate FIRST (same invariant as the engine: submitted ==
        # sum of outcome counters, malformed requests touch nothing)
        req = RouterRequest(tokens, token_types, deadline_ms,
                            decode=decode or None, stream=stream,
                            model_id=model_id, tenant=tenant,
                            tenant_class=tenant_class)
        self._bump("submitted")
        # journal only requests that LOOK admittable: shedding must
        # stay cheap under overload (no peer round trip per refusal).
        # The authoritative admission check re-runs after journaling;
        # if the queue drained in between (pre-check refused, final
        # check would admit an UNJOURNALED request), go around once
        # more so every admitted request really is journaled — the
        # second lap journals unconditionally.
        for lap in range(2):
            if (self._c_ha is not None and req.cid is None
                    and (lap > 0
                         or self._refusal_peek() is None)):
                req.cid = str(cid) if cid is not None else req.trace_id
                # journal BEFORE the request can be dispatched: the
                # ack wait (bounded) is the durability cost of the
                # zero-loss contract; a missing/slow peer degrades to
                # unjournaled
                self._ha_journal(req)
            # decide under the lock, account/raise OUTSIDE it
            # (self._cond shares self._lock, which _bump needs —
            # non-reentrant)
            with self._cond:
                refusal = self._refusal_locked()
                if (refusal is None and self._c_ha is not None
                        and req.cid is None):
                    continue        # drained mid-flight: journal first
                if refusal is None:
                    self._queue.append(req)
                    self._pending += 1
                    if req.cid is not None:
                        self._live_cids[req.cid] = req.future
                        while len(self._live_cids) > self._adopted_cap:
                            self._live_cids.popitem(last=False)
                    self._cond.notify()
            break
        if refusal is None:
            return req.future
        # refused after journaling: release, or the peer would adopt
        # (and execute) a request this router never accepted
        self._ha_release(req)
        if refusal == "stopped":
            self._bump("rejected_stopped")
            req.span.end(error="rejected: router not running")
            raise EngineStoppedError("serving router is not running")
        _events.emit("router_shed", reason=refusal,
                     router_id=self.router_id, trace_id=req.trace_id)
        # shed traces are tail-sampling KEEPs by contract, same as the
        # engine's: the operator debugging overload wants exactly these
        req.span.set_attr(shed=refusal).force_keep() \
           .end(error=f"shed: {refusal}")
        if refusal == "no_engine":
            self._bump("shed_no_engine")
            raise NoEngineAvailableError("no routable engine (fleet down)")
        self._bump("shed_queue_full")
        raise QueueFullError(
            f"router queue full (depth {self._max_queue_depth})")

    def _refusal_locked(self):
        """The admission decision (caller holds ``_lock``): None =
        admittable, else the refusal reason."""
        if not self._started or self._closed:
            return "stopped"
        if not any(s.routable for s in self._seats.values()):
            return "no_engine"
        if len(self._queue) >= self._max_queue_depth:
            return "queue_full"
        return None

    def _refusal_peek(self):
        """Advisory admission look (takes and releases the lock) —
        the cheap pre-check that keeps sheds from paying peer I/O."""
        with self._lock:
            return self._refusal_locked()

    def infer(self, tokens, token_types=None, deadline_ms=None,
              timeout=None):
        return self.submit(tokens, token_types, deadline_ms).result(timeout)

    # -- dispatch ----------------------------------------------------------
    def _run_dispatch(self):
        while True:
            with self._cond:
                while not self._queue and not self._exit_locked():
                    self._cond.wait(0.2)
                if not self._queue:
                    if self._exit_locked():
                        return
                    continue
                req = self._queue.popleft()
                seat = None
                if not req.expired():
                    seat = self._pick_locked(req.tried, req.model_id)
                    if seat is not None:
                        seat.outstanding += 1
                        seat.dispatched += 1
            if seat is None:
                if req.expired():
                    self._finish(req, DeadlineExceededError(
                        f"request {req.trace_id} deadline exceeded "
                        "before dispatch"), "expired")
                else:
                    # failover exhausted or fleet down: an explicit
                    # shed, never a silent drop
                    self._bump_shed_no_engine(req)
                continue
            # a deadline that lapsed since the in-lock check still
            # dispatches: the engine re-checks at drain, and the
            # picked seat's outstanding count must balance its _on_done
            req.engine_id = seat.engine_id
            self._c_dispatch.labels(engine_id=seat.engine_id).inc()
            self._note_trace_engine(req.trace_id, seat.engine_id)
            try:
                seat.dispatch(req, self._dispatch_timeout_s,
                              self._on_done)
            except Exception as e:  # sync admission failure (queue
                # full, stopped) funnels through the same completion
                # path so failover/accounting stay uniform
                self._on_done(seat, req, e, None)

    def _exit_locked(self):
        return self._closed and (self._abort or self._pending == 0)

    def _pick_locked(self, exclude, model_id=None):
        # WEIGHTED least outstanding: score = (outstanding + 1) /
        # weight, ties break round-robin (least recently picked). With
        # every weight at 1.0 (weights off, or a healthy fleet) the
        # order is exactly the classic least-outstanding; a seat shed
        # to weight w gets ~w of a full share under load and only
        # overflow traffic when idle. A request naming a model only
        # considers seats advertising it (unknown hosted sets route
        # optimistically — a 404 there is typed and propagates).
        best = best_score = None
        for seat in self._seats.values():
            if not seat.routable or seat.token in exclude \
                    or not seat.hosts(model_id):
                continue
            score = ((seat.outstanding + 1.0)
                     / max(seat.weight, self._w_floor))
            if best is None or (score, seat.last_picked) \
                    < (best_score, best.last_picked):
                best, best_score = seat, score
        if best is not None:
            best.last_picked = next(self._pick_seq)
        return best

    def _bump_shed_no_engine(self, req):
        self._bump("shed_no_engine")
        _events.emit("router_shed", reason="no_engine",
                     router_id=self.router_id, trace_id=req.trace_id,
                     tried=sorted(req.tried))
        req.span.set_attr(shed="no_engine")
        self._finish(req, NoEngineAvailableError(
            "no routable engine"
            + (f" (tried {sorted(req.tried)})" if req.tried else "")),
            None, force_keep=True)

    def _on_done(self, seat, req, exc, value, cost=None,
                 breakdown=None):
        with self._lock:
            seat.outstanding = max(0, seat.outstanding - 1)
        if exc is None:
            self._bump("completed")
            total_ms = (time.monotonic() - req.t_submit) * 1e3
            # exemplar on the fleet latency histogram: links a firing
            # fleet_latency alert to a retrievable cross-engine trace
            self.total_ms.observe(
                total_ms, exemplar=slow_exemplar(
                    req.trace_id, total_ms, self._exemplars))
            req.span.set_attr(engine=req.engine_id,
                              requeues=req.requeues).end()
            if cost is not None:
                # the engine's amortized bill rides through to the
                # router's caller (remote seats carry it in the
                # /submit body) so cost attribution survives fronting
                req.future.cost = cost
            if breakdown is not None:
                # the ENGINE's critical-path decomposition, relayed
                # verbatim (wire and HTTP seats carry it in the reply
                # body, local seats on the future) — the caller sees
                # the same breakdown it would have engine-direct
                req.future.breakdown = breakdown
            self._observe_router_stages(req, total_ms)
            req.future.set_result(value)
            # shadow-diff mirror: strictly AFTER the live future has
            # resolved — fire-and-forget at the candidate seat; the
            # live caller never waits on (or sees) the shadow leg
            if self._shadow is not None:
                try:
                    self._shadow.mirror(req, value, total_ms)
                except Exception as e:
                    _events.emit("shadow_mirror_error",
                                 router_id=self.router_id,
                                 trace_id=req.trace_id, error=repr(e))
            self._ha_release(req)
            self._resolve()
            return
        if isinstance(exc, _FAILOVER_ERRORS) and not req.expired():
            # the ENGINE failed, not the request: unroutable-on-death
            # + re-queue at the front for a sibling. The queue insert
            # and the abort check share one critical section — an
            # abort stop() racing in here must not strand the request
            # in a queue whose dispatcher already exited.
            if isinstance(exc, (EngineStoppedError, RemoteEngineError)) \
                    and not seat.closed:
                # a REMOVED seat's failures must not touch the gauges
                # of a replacement registered under the same id
                self._mark(seat, up=False,
                           reason=f"dispatch: {type(exc).__name__}")
                seat.last_error = repr(exc)
            with self._cond:
                requeued = not self._abort
                if requeued:
                    # tried must grow BEFORE the dispatcher can re-pop
                    # the request, or it may re-pick this same seat
                    # (generation tokens: a same-id REPLACEMENT seat
                    # stays a fresh candidate)
                    req.requeues += 1
                    req.tried.add(seat.token)
                    self._queue.appendleft(req)
                    self._cond.notify()
            if requeued:
                self._bump("requeued")
                self._c_failover.labels(engine_id=seat.engine_id).inc()
                _events.emit("router_failover", router_id=self.router_id,
                             trace_id=req.trace_id,
                             from_engine=seat.engine_id,
                             error=repr(exc), requeues=req.requeues)
                return
        if isinstance(exc, DeadlineExceededError):
            counter = "expired"
        elif isinstance(exc, EngineStoppedError):
            counter = "cancelled"
        else:
            counter = "failed"
        self._finish(req, exc, counter)

    def _finish(self, req, exc, counter, force_keep=False):
        if counter is not None:
            self._bump(counter)
        if force_keep:
            req.span.force_keep()
        req.span.end(error=repr(exc))
        req.future.set_exception(exc)
        self._ha_release(req)
        self._resolve()

    def _observe_router_stages(self, req, total_ms):
        """Feed the ROUTER-owned stages (dispatch transit, HA-journal
        ack) into this router's /whyslow aggregator. Only the stages
        the router itself timed are billed here — the engine's own
        decomposition aggregates engine-side and reaches the fleet
        view through the /whyslow merge, so nothing double-counts."""
        if not req.stages:
            return
        per = {}
        for name, a, b in req.stages:
            if name in ("dispatch", "ha_ack"):
                per[name] = per.get(name, 0.0) + (b - a)
        if not per:
            return
        rb = {"wall_ms": total_ms, "trace_id": req.trace_id,
              "stages": [{"stage": s, "ms": round(v * 1e3, 3),
                          "share": (round(v * 1e3 / total_ms, 4)
                                    if total_ms > 0 else 0.0)}
                         for s, v in per.items()],
              "unattributed_ms": 0.0}
        _attribution.aggregator(self.router_id).observe(
            rb, tenant_class=req.tenant_class, model=req.model_id,
            trace_id=req.trace_id)

    def _resolve(self):
        with self._cond:
            self._pending -= 1
            self._cond.notify_all()

    def _bump(self, name, n=1):
        with self._lock:
            self._c[name] += n
        self._reg_c[name].inc(n)

    def count(self, name):
        with self._lock:
            return self._c[name]

    def _note_trace_engine(self, trace_id, engine_id):
        with self._lock:
            ids = self._trace_engines.setdefault(trace_id, [])
            if engine_id not in ids:
                ids.append(engine_id)
            self._trace_engines.move_to_end(trace_id)
            while len(self._trace_engines) > self._trace_engines_cap:
                self._trace_engines.popitem(last=False)

    # -- health scoreboard -------------------------------------------------
    def _run_poll(self):
        while not self._stop_evt.wait(self._poll_interval_s):
            try:
                self._poll_once()
            except Exception as e:
                # a poll failure must not kill routing, but a silent
                # one hides a scoreboard gone stale — leave a trace
                _events.emit("router_poll_error",
                             router_id=self.router_id, error=repr(e))

    def _poll_once(self):
        now = time.monotonic()
        with self._lock:
            seats = list(self._seats.values())
        up_count = 0
        signals = {}
        for seat in seats:
            try:
                ok, snap = seat.health()
            except Exception as e:
                ok, snap = False, {"error": repr(e)}
            beat_age = snap.get("seconds_since_beat")
            allowed = _recorder.stall_seconds()
            if snap.get("compiling"):
                # an open first-visit compile window widens the
                # allowance by the SAME finite grace as the engine's
                # own watchdog — tens-of-seconds compiles are
                # progress, but a compile outliving even the grace is
                # a wedge and must not stay routable forever
                allowed += envvars.get(
                    "MXNET_TPU_WATCHDOG_COMPILE_GRACE_S")
            if ok and beat_age is not None and beat_age > allowed \
                    and (snap.get("queue_depth") or 0) > 0:
                # alive but WEDGED: the worker loop stopped beating
                # with work queued — unroutable, same as unreachable
                ok = False
                snap = dict(snap, error=f"stalled: worker beat "
                            f"{beat_age:.1f}s old with queued work")
            if ok:
                mcount = snap.get("manifest_shapes")
                if mcount is not None \
                        and mcount != seat._manifest_count:
                    # visited-shape set changed since the last collect:
                    # pull the engine's manifest and fold it into the
                    # fleet union (persisted for warm restarts). A
                    # failing collect must not abort the poll round —
                    # the remaining seats still need health updates.
                    try:
                        m = seat.warmup_manifest()
                        if m is not None:
                            seat._manifest_count = mcount
                            self._fold_manifest(m)
                    except Exception as e:
                        _events.emit("router_manifest_error",
                                     router_id=self.router_id,
                                     engine_id=seat.engine_id,
                                     error=repr(e))
            if ok:
                seat.consecutive_failures = 0
                seat.queue_depth = snap.get("queue_depth")
                models = snap.get("models")
                if isinstance(models, dict):
                    # the hosted-model advertisement: feeds the
                    # model-aware pick and the canary's version
                    # fingerprint (a hot-swap re-TOFUs the golden)
                    seat.models = dict(models)
                lat = (snap.get("latency") or {}).get("total") or {}
                seat.p95_ms = lat.get("p95_ms")
                completed = (snap.get("counters") or {}).get("completed")
                if (completed is not None
                        and seat._prev_completed is not None
                        and seat._prev_poll is not None
                        and now > seat._prev_poll):
                    seat.qps = max(0.0, round(
                        (completed - seat._prev_completed)
                        / (now - seat._prev_poll), 2))
                seat._prev_completed = completed
                seat._prev_poll = now
                self._mark(seat, up=True)
                if self._weights_on:
                    signals[seat] = self._seat_signals(seat, snap)
            else:
                seat.consecutive_failures += 1
                seat.last_error = snap.get("error") or "health check failed"
                if seat.consecutive_failures >= self._fail_after:
                    self._mark(seat, up=False, reason=seat.last_error)
            self._g_queue_depth.labels(engine_id=seat.engine_id) \
                .set(seat.queue_depth or 0)
            if seat.routable:
                up_count += 1
            try:
                # wire upkeep rides the same poll cadence: blocking
                # connect/handshake + in-flight timeout sweep happen
                # HERE so the dispatch path never blocks on either
                seat.maintain()
            except Exception as e:
                _events.emit("router_wire_maintain_error",
                             router_id=self.router_id,
                             engine_id=seat.engine_id, error=repr(e))
        if self._weights_on:
            self._update_weights(signals)
        self._g_fleet.set(up_count)
        # the shadow mirror's wire connection rides the same poll
        # cadence as the seats' — blocking connect work stays here,
        # never on the dispatch or completion paths
        if self._shadow is not None:
            try:
                self._shadow.maintain()
            except Exception as e:
                _events.emit("shadow_maintain_error",
                             router_id=self.router_id, error=repr(e))
        self._maintain_peer()

    # -- SLO-aware routing weights (poll thread) ---------------------------
    def _seat_signals(self, seat, snap):
        """One seat's health signals for the weight fold: the max
        short-window burn rate over its ratio objectives (``/slo``),
        the poll-windowed device_s/1k-tokens EMA off the ``/stats``
        cost totals, and the canary probe latency EMA. Poll thread
        only."""
        fetch = seat._sig_tick % self._slo_every == 0
        seat._sig_tick += 1
        if fetch:
            from ..telemetry.slo import max_short_burn
            try:
                slo = seat.slo_snapshot()
            except Exception:
                slo = None
            seat.burn = burn = max_short_burn(slo)
        else:
            burn = seat.burn        # throttled: reuse the last fetch
        costs = snap.get("costs") or {}
        cur = (costs.get("request_s"), costs.get("valid_tokens"))
        prev = seat._prev_cost
        seat._prev_cost = cur
        if (prev is not None and None not in cur
                and None not in prev and cur[1] - prev[1] > 0):
            inst = (cur[0] - prev[0]) * 1e3 / (cur[1] - prev[1])
            if inst >= 0:
                seat.cost_rate = (inst if seat.cost_rate is None
                                  else 0.5 * seat.cost_rate
                                  + 0.5 * inst)
                seat._cost_age = 0
        else:
            # no fresh tokens this poll: the EMA is aging. A shed
            # seat stops receiving traffic, so a stale-high cost
            # reading must EXPIRE or it would pin the penalty (and
            # the floor weight) forever — no data is no signal,
            # exactly like a burn rate over an empty window
            seat._cost_age += 1
        cost = seat.cost_rate if seat._cost_age <= 5 else None
        canary = self._canary
        lat = (canary.latency_ms(seat.engine_id)
               if canary is not None else None)
        return {"burn": burn, "cost": cost, "canary": lat}

    def _update_weights(self, signals):
        """Fold each healthy seat's signals into its routing weight.
        Burn rate is judged absolutely (1x is sustainable, the page
        factor 14.4x is a full shed); cost and canary latency are
        judged RELATIVE to the median of the other seats (a uniform
        slowdown is capacity, not a hot-spot)."""
        def _others_median(key, me):
            xs = sorted(v[key] for s, v in signals.items()
                        if s is not me and v.get(key) is not None)
            return xs[len(xs) // 2] if xs else None

        for seat, v in signals.items():
            penalty = 0.0
            burn = v.get("burn")
            if burn is not None and burn > 1.0:
                penalty = max(penalty, min(1.0, (burn - 1.0) / 13.4))
            for key in ("cost", "canary"):
                mine = v.get(key)
                ref = _others_median(key, seat)
                if mine is None or ref is None or ref <= 0:
                    continue
                ratio = mine / ref
                if ratio > 1.25:
                    # 25% over the fleet is noise; 3x is a full shed
                    penalty = max(penalty,
                                  min(1.0, (ratio - 1.25) / 1.75))
            self._step_weight(seat,
                              max(self._w_floor, 1.0 - penalty))

    def _step_weight(self, seat, target):
        """One hysteresis + smoothing step: healthy seats pin 1.0;
        a target at/below the enter bound flips the seat DEGRADED
        (weight then tracks the target with gain alpha); recovery
        needs the target back above the exit bound for
        ``_W_OK_POLLS`` consecutive polls — no flapping on a noisy
        boundary signal."""
        prev_hys = seat.hys
        if seat.hys == "healthy":
            if target <= _W_ENTER:
                seat.hys = "degraded"
                seat.ok_polls = 0
        elif target >= _W_EXIT:
            seat.ok_polls += 1
            if seat.ok_polls >= _W_OK_POLLS:
                seat.hys = "healthy"
        else:
            seat.ok_polls = 0
        if seat.hys == "degraded":
            seat.weight += self._w_gain * (target - seat.weight)
            seat.weight = max(self._w_floor, min(1.0, seat.weight))
        else:
            seat.weight = 1.0
        self._g_weight.labels(engine_id=seat.engine_id) \
            .set(round(seat.weight, 4))
        if seat.hys != prev_hys:
            _events.emit("router_engine_weight",
                         router_id=self.router_id,
                         engine_id=seat.engine_id, state=seat.hys,
                         weight=round(seat.weight, 4),
                         target=round(target, 4))

    def _fold_manifest(self, manifest):
        """Union one engine's manifest into the fleet manifest; when
        the union GROWS, persist it (MXNET_TPU_WARMUP_MANIFEST) so a
        restarting engine finds the fleet's whole working set on disk
        even after every live engine is gone. The in-memory union is
        seeded from the persisted file, and an empty shape set is
        never written: a freshly restarted fleet reporting zero
        visited shapes must not clobber the previous run's manifest
        (which is exactly what the next warm restart needs)."""
        with self._lock:
            need_seed = self._fleet_manifest is None
        seed = compile_cache.load_manifest() if need_seed else None
        with self._lock:
            prev = self._fleet_manifest
            if prev is None:
                prev = seed
            merged = compile_cache.merge_manifests([prev, manifest])
            if merged is None:
                return
            grew = (prev is None
                    or len(merged["shapes"]) > len(prev["shapes"])
                    or set(merged["engines"]) != set(prev["engines"]))
            self._fleet_manifest = merged
        self._g_manifest.set(len(merged["shapes"]))
        if grew and merged["shapes"]:
            path = compile_cache.save_manifest(merged)
            _events.emit("router_warmup_manifest",
                         router_id=self.router_id,
                         shapes=len(merged["shapes"]),
                         engines=merged["engines"], path=path)

    def warmup_manifest(self):
        """The fleet-union warmup manifest (``/warmup`` on the
        router's exposition server; falls back to the persisted file
        when no engine has reported yet — e.g. right after a full
        fleet restart)."""
        with self._lock:
            if self._fleet_manifest is not None:
                return dict(self._fleet_manifest)
        return compile_cache.load_manifest()

    # -- router active/active HA -------------------------------------------
    def set_peer(self, url):
        """Configure (or repoint) the active/active peer AFTER
        construction — the two-router bootstrap needs each other's
        exposed URL, which only exists post-``expose()``. Starts the
        HA journal listener immediately when this router is already
        exposed. A no-op under ``MXNET_TPU_ROUTER_HA=0`` (the
        disabled path registers no family and pays no per-request
        cid cost)."""
        if not self._ha_on:
            return self
        self._peer_url = str(url).rstrip("/")
        self._ha_setup()
        with self._lock:
            expo = self._expo
            if expo is not None:
                self._ha_listen(expo.host)
        return self

    def _ha_listen(self, host):
        """Start the HA journal listener (caller holds ``_lock``)."""
        if self._ha is not None or not self._ha_on:
            return
        from .wire import WireListener
        try:
            self._ha = WireListener(
                owner_id=self.router_id, handler=self._ha_handle,
                host=host,
                port=envvars.get("MXNET_TPU_ROUTER_HA_PORT"),
                side="ha")
            self._ha_setup()
        except OSError as e:
            _events.emit("router_ha_listen_error",
                         router_id=self.router_id, error=repr(e))

    def _ha_setup(self):
        """Register the HA counter family (the activity gate: journal
        and cid bookkeeping run only once this exists — HA off means
        no family and zero per-request cost)."""
        if self._c_ha is None:
            self._c_ha = _REGISTRY.counter(
                "mxnet_tpu_router_ha_total",
                "router active/active HA events: journal sent/received"
                "/released, ack misses, skipped (no peer link), orphan "
                "adoptions, cid dedup hits, journal-cap drops",
                ("event",))

    def _ha_count(self, event):
        if self._c_ha is not None:
            self._c_ha.labels(event=event).inc()

    def _ha_handle(self, payload):
        """The inbound journal surface (wire-listener handler, runs on
        the peer connection's reader thread — instant bookkeeping
        only)."""
        op = payload.get("op") if isinstance(payload, dict) else None
        if op == "journal":
            cid = str(payload.get("cid"))
            entry = {"tokens": payload.get("tokens"),
                     "token_types": payload.get("token_types"),
                     "deadline_ms": payload.get("deadline_ms"),
                     "decode": payload.get("decode"),
                     "stream": bool(payload.get("stream")),
                     "model_id": payload.get("model_id"),
                     "tenant": payload.get("tenant"),
                     "tenant_class": payload.get("tenant_class"),
                     "router_id": payload.get("router_id"),
                     "t": time.monotonic()}
            dropped = 0
            with self._lock:
                self._journal[cid] = entry
                self._journal.move_to_end(cid)
                while len(self._journal) > self._journal_cap:
                    self._journal.popitem(last=False)
                    dropped += 1
            self._ha_count("journal_rx")
            for _ in range(dropped):
                self._ha_count("journal_drop")
            return {"ok": True}
        if op == "release":
            with self._lock:
                self._journal.pop(str(payload.get("cid")), None)
            self._ha_count("release")
            return {"ok": True}
        raise ValueError(f"unknown HA op {op!r}")

    def _ha_lookup(self, cid):
        """Resubmit dedupe: the future already serving this cid (live
        or adopted), or None. A cid found in the PEER's journal means
        the peer accepted it and died before answering — the client
        re-drove it here, so the entry is consumed (counted an
        adoption) and the resubmitted payload is executed once."""
        with self._lock:
            fut = self._live_cids.get(cid)
            if fut is None:
                fut = self._adopted.get(cid)
            entry = None
            if fut is None:
                entry = self._journal.pop(cid, None)
        if fut is not None:
            self._ha_count("dedup")
            _events.emit("router_ha_dedup", router_id=self.router_id,
                         cid=cid)
            return fut
        if entry is not None:
            self._ha_count("adopt")
            _events.emit("router_ha_adopt", router_id=self.router_id,
                         cid=cid, count=1, path="resubmit")
        return None

    def _ha_journal(self, req):
        """Journal one admitted request to the peer and wait (bounded)
        for the ack — the request must be durable on the peer BEFORE
        it can be dispatched, or a death in between loses it. No live
        peer link degrades to unjournaled (counted ``skip``) —
        availability over durability."""
        peer = self._peer
        if peer is None or not peer.has_live():
            if self._peer_url:
                self._ha_count("skip")
            return
        acked = threading.Event()
        box = {}
        t_ack0 = time.monotonic()

        def _on_ack(exc, body):
            # the reader delivers ERROR frames with exc=None and the
            # error in the body: a peer that REFUSED the journal op
            # must not count as durable
            box["ok"] = (exc is None
                         and not (body or {}).get("error_type"))
            acked.set()

        try:
            peer.dispatch({"op": "journal", "cid": req.cid,
                           "tokens": req.tokens,
                           "token_types": req.token_types,
                           "deadline_ms": req.remaining_ms(),
                           "decode": req.decode,
                           "stream": req.stream,
                           "model_id": req.model_id,
                           "tenant": req.tenant,
                           "tenant_class": req.tenant_class,
                           "router_id": self.router_id},
                          _on_ack, self._ha_ack_s)
        except WireError:
            self._ha_count("skip")
            return
        ok = acked.wait(self._ha_ack_s) and box.get("ok")
        # the durability wait is on the request's critical path — a
        # slow peer surfaces as an ``ha_ack`` stage in /whyslow
        _attribution.stamp(req, "ha_ack", t_ack0, time.monotonic(),
                           attrs={"acked": bool(ok)})
        if ok:
            self._ha_count("journal")
        else:
            self._ha_count("ack_miss")

    def _ha_release(self, req):
        """Tell the peer this cid resolved (fire-and-forget): its
        journal entry must not outlive the request, or a later death
        would re-execute completed work."""
        cid = req.cid
        if cid is None:
            return
        with self._lock:
            self._live_cids.pop(cid, None)
        peer = self._peer
        if peer is None:
            return
        try:
            peer.dispatch({"op": "release", "cid": cid},
                          lambda exc, body: None, self._ha_ack_s)
        except WireError:
            pass

    def _maintain_peer(self):
        """Poll-thread peer upkeep: liveness (any HTTP answer from the
        peer's /healthz means the PROCESS is alive — an unhealthy
        fleet is not a dead router), journal-link connect/sweep, and
        the death edge that triggers orphan adoption."""
        if not (self._ha_on and self._peer_url):
            return
        if not self._peer_recon.ready():
            return      # backing off a recently failed peer dial
        alive, hz = True, {}
        try:
            # capped at the poll period: a slow-but-answering peer
            # must not stretch every seat-health tick
            with urllib.request.urlopen(
                    self._peer_url + "/healthz",
                    timeout=min(2.0, max(0.25,
                                         self._poll_interval_s))) as r:
                hz = json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            try:
                hz = json.loads(e.read().decode())
            except Exception:
                hz = {}
        except Exception:
            alive = False
        if alive:
            self._peer_recon.succeeded()
            self._peer_fails = 0
            self._peer_ha_port = hz.get("ha_port") or self._peer_ha_port
            rid = hz.get("router_id")
            if rid is not None:
                self._peer_rid = str(rid)
            if self._peer_alive is False:
                _events.emit("router_peer_state",
                             router_id=self.router_id,
                             peer=self._peer_rid or self._peer_url,
                             state="up")
            self._peer_alive = True
            port = self._peer_ha_port
            if port:
                peer = self._peer
                if peer is not None and peer.port != int(port):
                    # peer restarted on a new HA port: rebuild
                    self._peer = None
                    peer.close()
                    peer = None
                if peer is None:
                    host = (urlsplit(self._peer_url).hostname
                            or "127.0.0.1")
                    peer = WireClient(host, int(port), conns=1,
                                      client_id=self.router_id,
                                      expect_engine_id=self._peer_rid)
                    self._peer = peer
                    self._ha_setup()
                peer.ensure()
                peer.sweep()
            return
        self._peer_recon.failed()
        self._peer_fails += 1
        if self._peer_alive is True \
                and self._peer_fails >= max(2, self._fail_after):
            self._peer_alive = False
            _events.emit("router_peer_state", router_id=self.router_id,
                         peer=self._peer_rid or self._peer_url,
                         state="down")
            try:
                self._adopt_orphans()
            except Exception as e:
                _events.emit("router_ha_adopt_error",
                             router_id=self.router_id, error=repr(e))

    def _adopt_orphans(self):
        """The peer died: every cid it journaled and never released is
        an in-flight request about to be lost — rebuild each as a
        RouterRequest and requeue it FRONT of the line (it has been
        waiting longest). A client resubmitting its cid attaches to
        the adopted future; a client that never comes back still gets
        the work completed (at-least-once). The cids are RESERVED in
        ``_adopted`` in the same critical section that empties the
        journal, so a resubmit racing this sweep attaches instead of
        being admitted as duplicate new work."""
        reserved = []               # (cid, entry, future)
        with self._cond:
            if self._closed:
                return 0
            entries = list(self._journal.items())
            self._journal.clear()
            for cid, e in entries:
                if cid in self._live_cids or cid in self._adopted:
                    continue
                fut = InferenceFuture()
                self._live_cids[cid] = fut
                self._adopted[cid] = fut
                reserved.append((cid, e, fut))
            while len(self._adopted) > self._adopted_cap:
                self._adopted.popitem(last=False)
        adopt = []
        for cid, e, fut in reserved:
            deadline_ms = e.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = (float(deadline_ms)
                               - (time.monotonic() - e["t"]) * 1e3)
                if deadline_ms <= 0:
                    # dead on its own deadline either way — but the
                    # reserved future must resolve for any attached
                    # resubmitter
                    fut.set_exception(DeadlineExceededError(
                        f"adopted request {cid} expired before its "
                        "peer's death was detected"))
                    continue
            try:
                req = RouterRequest(e["tokens"], e.get("token_types"),
                                    deadline_ms,
                                    decode=e.get("decode"),
                                    stream=bool(e.get("stream")),
                                    model_id=e.get("model_id"),
                                    tenant=e.get("tenant"),
                                    tenant_class=e.get("tenant_class"))
            except Exception as exc:
                fut.set_exception(ServingError(
                    f"adopted journal entry {cid} unusable: {exc!r}"))
                continue
            # the RESERVED future is the request's identity (clients
            # may already hold it via a resubmit attach)
            req.future = fut
            fut.trace_id = req.trace_id
            req.cid = cid
            req.adopted = True
            req.span.set_attr(adopted=1)
            adopt.append(req)
        if not adopt:
            _events.emit("router_peer_state", router_id=self.router_id,
                         peer=self._peer_rid or self._peer_url,
                         state="adopted")
            return 0
        with self._cond:
            if self._closed:
                for req in adopt:
                    req.future.set_exception(EngineStoppedError(
                        "router stopped during orphan adoption"))
                return 0
            for req in reversed(adopt):
                self._queue.appendleft(req)
            self._pending += len(adopt)
            self._cond.notify_all()
        for _ in adopt:
            self._ha_count("adopt")
        self._bump("adopted", len(adopt))
        _events.emit("router_ha_adopt", router_id=self.router_id,
                     peer=self._peer_rid or self._peer_url,
                     count=len(adopt), path="peer_death")
        # the peer's orphans are in OUR hands now: release the
        # incident hold (the outage is handled, not ongoing)
        _events.emit("router_peer_state", router_id=self.router_id,
                     peer=self._peer_rid or self._peer_url,
                     state="adopted")
        return len(adopt)

    def die(self):
        """Simulate abrupt router death (the chaos drill's
        ``kill_router`` fault and the HA tests' crash surface): stop
        serving WITHOUT draining, resolving, or handing anything off —
        in-flight work is orphaned exactly as a SIGKILL would leave
        it. The peer's journal adoption (and clients' cid resubmits)
        are the recovery path under test. After ``die()``, ``stop()``
        is a no-op."""
        _events.emit("router_die", router_id=self.router_id)
        # sever the OUTWARD surfaces first — peer link, journal
        # listener, exposition server — exactly what a SIGKILL cuts
        # instantly. In-process work may still complete during the
        # teardown window, but no release/journal/reply escapes it,
        # so the peer's view matches a real crash.
        with self._lock:
            expo, self._expo = self._expo, None
            ha, self._ha = self._ha, None
            peer, self._peer = self._peer, None
        if peer is not None:
            peer.close()
        if ha is not None:
            ha.close()
        if expo is not None:
            expo.close()
        with self._cond:
            self._died = True
            self._closed = True
            self._abort = True
            stranded = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        # a real SIGKILL severs every client connection instantly; the
        # in-process simulation must match it — stranded futures fail
        # NOW so a blocked /submit handler answers (503) and its
        # client re-drives the cid at the survivor, instead of hanging
        # out a long timeout on a half-dead router
        for req in stranded:
            req.span.end(error="router died")
            req.future.set_exception(EngineStoppedError(
                "router died with the request undispatched"))
        self._stop_evt.set()
        _recorder.unregister_probe(self._probe_name)
        _recorder.remove_bundle_section("router_scoreboard")
        if self._canary is not None:
            self._canary.stop()
        if self._slo is not None:
            self._slo.stop()
        if self._history is not None:
            self._history.stop()
        with self._lock:
            seats = list(self._seats.values())
        for seat in seats:
            seat.close()
        for t in (self._dispatcher, self._poller):
            if t is not None and t is not threading.current_thread():
                t.join(timeout=5.0)

    def _mark(self, seat, up, reason=None):
        if seat.routable == up and seat.up == up:
            return
        seat.up = up
        seat.routable = up
        seat.last_change = time.time()
        self._g_up.labels(engine_id=seat.engine_id).set(1 if up else 0)
        _events.emit("router_engine_state", router_id=self.router_id,
                     engine_id=seat.engine_id,
                     state="up" if up else "down", reason=reason)
        if up:
            seat.consecutive_failures = 0
            seat.last_error = None

    def _watchdog_probe(self):
        """None while the whole fleet is routable; an anomaly dict
        (which the flight bundle's router_scoreboard.json expands on)
        when any engine is down."""
        if not self.running:
            return None
        with self._lock:
            down = [s.engine_id for s in self._seats.values()
                    if not s.routable]
            total = len(self._seats)
        if not down:
            return None
        kind = ("router_all_engines_down" if len(down) == total
                else "router_engine_down")
        return {"kind": kind, "engines_down": down,
                "engines_total": total}

    def scoreboard(self):
        """Per-engine health rows (the /stats ``engines`` section and
        the flight bundle's fleet view)."""
        with self._lock:
            return {sid: seat.row() for sid, seat in self._seats.items()}

    def snapshot(self):
        board = self.scoreboard()
        with self._lock:
            counters = dict(self._c)
            queue_depth = len(self._queue)
            pending = self._pending
            manifest_shapes = (len(self._fleet_manifest["shapes"])
                               if self._fleet_manifest else 0)
        return {"router_id": self.router_id,
                "running": self.running,
                "counters": counters,
                "queue_depth": queue_depth,
                "pending": pending,
                "manifest_shapes": manifest_shapes,
                "engines": board,
                "engines_up": sum(1 for r in board.values()
                                  if r["routable"]),
                "engines_total": len(board),
                "latency": {"total": self.total_ms.snapshot()},
                "dispatch_overhead": self.dispatch_overhead.snapshot()}

    # -- aggregated observability plane ------------------------------------
    def _remote_seats(self, engine_filter=None):
        """Remote seats worth scraping: unroutable seats are SKIPPED —
        a dead endpoint would stall the aggregated reply by a full
        http timeout per scrape (past Prometheus's own scrape budget)
        while contributing nothing."""
        with self._lock:
            return [s for s in self._seats.values()
                    if isinstance(s, _RemoteSeat) and s.routable
                    and (engine_filter is None
                         or s.engine_id in engine_filter)]

    def metrics_text(self):
        """The fleet exposition: this process's registry (router
        families + every LOCAL engine's labeled families) scrape-merged
        with each routable remote engine's ``/metrics``."""
        from ..telemetry.expo import merge_prometheus_texts

        texts = [_REGISTRY.render_prometheus()]
        for seat in self._remote_seats():
            try:
                texts.append(seat.metrics_text())
            except Exception:
                self._c_scrape_err.labels(engine_id=seat.engine_id).inc()
        return merge_prometheus_texts(texts)

    def traces_summary(self):
        """Fleet /traces: the local span ring (router + in-process
        engines) merged with every routable remote engine's
        tail-sampled ring, each kept trace annotated with the engines
        that served it."""
        parts = [(None, _spans.traces_summary())]
        for seat in self._remote_seats():
            parts.append((seat.engine_id, seat.traces_summary()))
        merged = _spans.merge_trace_summaries(parts)
        with self._lock:
            known = dict(self._trace_engines)
        for rec in merged["kept"]:
            for eid in known.get(rec["trace_id"], ()):
                if eid not in rec["engines"]:
                    rec["engines"].append(eid)
        return merged

    def get_trace(self, trace_id):
        """Fleet /traces/<id>: one merged span tree across every ring
        that kept the trace — engine-side spans parent under the
        ``router/request`` root via the propagated span id. When the
        router dispatched the trace itself it queries only the engines
        that served it; unknown ids fan out to every routable remote
        (the trace may predate this router or be engine-local)."""
        with self._lock:
            known = self._trace_engines.get(trace_id)
        parts = [(None, _spans.get_trace(trace_id))]
        for seat in self._remote_seats(engine_filter=set(known)
                                       if known else None):
            parts.append((seat.engine_id, seat.get_trace(trace_id)))
        return _spans.merge_trace_records(parts)

    def cost_table(self):
        """The fleet ``/costs`` body: every routable engine's
        per-bucket cost ledger (local seats read the handle, remote
        seats scrape their ``/costs``), merged into one fleet table —
        per-bucket sums across engines plus fleet totals with the
        derived cost-per-request / cost-per-1k-tokens rates. The books
        are cumulative, so they must survive seats dying: every seat
        is asked regardless of routability (a stopped LOCAL engine's
        ledger still reads; remote seats fall back to their last
        fetched table) and ``remove_engine`` retires a seat's final
        ledger into the merge. Only a seat that never produced a table
        contributes nothing — named in ``missing`` rather than
        stalling the reply."""
        from .metrics import CostLedger

        engines = {}
        missing = []
        with self._lock:
            seats = list(self._seats.values())
            retired = dict(self._retired_costs)
        for seat in seats:
            table = seat.cost_table()
            if table is None:
                missing.append(seat.engine_id)
                continue
            engines[seat.engine_id] = table
        fleet_buckets = {}
        for table in list(engines.values()) + list(retired.values()):
            for blen, row in (table.get("buckets") or {}).items():
                fleet_buckets.setdefault(str(blen), []).append(row)
        fleet = {b: CostLedger._derive(merge_cost_buckets(rows))
                 for b, rows in sorted(fleet_buckets.items(),
                                       key=lambda kv: int(kv[0]))}
        totals = CostLedger._derive(
            merge_cost_buckets(list(fleet.values())))
        out = {"router_id": self.router_id, "engines": engines,
               "fleet": fleet, "totals": totals, "missing": missing}
        if retired:
            out["retired"] = retired
        return out

    @property
    def alerts(self):
        """The router's fleet :class:`~mxnet_tpu.telemetry.alerts.
        AlertDaemon` (None when ``MXNET_TPU_SLO=0`` or before
        ``start``) — drills drive ``evaluate_once`` / add rules
        through it."""
        return self._slo

    def slo_snapshot(self):
        """The fleet ``/slo`` body: the router's own objectives
        (availability across failover, fleet latency, engines-up
        fraction) plus every seat's seat-level SLO snapshot under
        ``engines`` (local handles read directly, remote seats
        scraped; seats without an evaluator are listed in
        ``missing``)."""
        if self._slo is None:
            out = {"owner": self.router_id, "enabled": False,
                   "objectives": {}}
        else:
            out = self._slo.evaluator.snapshot()
        with self._lock:
            seats = list(self._seats.values())
        engines, missing = {}, []
        for seat in seats:
            snap = seat.slo_snapshot()
            if snap is None:
                missing.append(seat.engine_id)
            else:
                engines[seat.engine_id] = snap
        out["engines"] = engines
        if missing:
            out["missing"] = missing
        return out

    def alerts_snapshot(self):
        """The fleet ``/alerts`` body: the router's own rule table
        plus every seat's, with fleet-wide firing/pending totals on
        top — one endpoint answers "what is burning, and WHERE"."""
        if self._slo is None:
            out = {"owner": self.router_id, "enabled": False,
                   "rules": [], "firing": 0, "pending": 0}
        else:
            out = self._slo.snapshot()
        with self._lock:
            seats = list(self._seats.values())
        engines = {}
        firing = out.get("firing", 0)
        pending = out.get("pending", 0)
        for seat in seats:
            snap = seat.alerts_snapshot()
            if snap is None:
                continue
            engines[seat.engine_id] = snap
            firing += snap.get("firing", 0)
            pending += snap.get("pending", 0)
        out["engines"] = engines
        out["fleet_firing"] = firing
        out["fleet_pending"] = pending
        return out

    def whyslow(self):
        """The fleet ``/whyslow`` body: the router's own stage table
        (dispatch transit, HA-journal ack) merged with every seat's
        per-stage breakdown — one endpoint answers "the fleet is slow,
        WHICH stage, on WHICH engine, and here is the worst trace".
        Seats without attribution (disabled, old peers) simply
        contribute nothing."""
        parts = []
        agg = _attribution.get_aggregator(self.router_id)
        if agg is not None:
            parts.append(agg.snapshot())
        with self._lock:
            seats = list(self._seats.values())
        for seat in seats:
            parts.append(seat.whyslow())
        return _attribution.merge_whyslow(parts, owner=self.router_id)

    def _whyslow_top(self):
        """Fleet top-stage rows for firing alert payloads, memoized
        for ~1s: /alerts renders every rule's payload in one pass and
        must not re-scrape every remote seat's /whyslow per rule.
        An EMPTY result only lives ~0.1s (one render pass): under a
        fast-burn overload the fleet rule can fire within the long
        TTL, and the page must not inherit a pre-traffic empty memo —
        it exists to say WHERE the fleet is slow."""
        now = time.monotonic()
        cached = self._whyslow_top_cache
        if cached is not None and \
                now - cached[0] < (1.0 if cached[1] else 0.1):
            return cached[1]
        top = (self.whyslow() or {}).get("top") or None
        if top:
            # the fleet merge ranks EVERY observed stage (so nothing
            # is truncation-blind); the page payload only wants the
            # leaders
            top = top[:envvars.get("MXNET_TPU_ATTRIBUTION_TOP")]
        self._whyslow_top_cache = (now, top)
        return top

    def capture_summary(self):
        """The fleet ``/capture`` body: every seat's capture-corpus
        summary under ``engines`` plus fleet record/byte totals (local
        handles read directly, remote seats scraped; seats without
        capture — disabled, old peers — land in ``missing``)."""
        from .capture import merge_summaries
        with self._lock:
            seats = list(self._seats.values())
        return merge_summaries(
            [(seat.engine_id, seat.capture_summary()) for seat in seats],
            owner=self.router_id)

    @property
    def shadow(self):
        """The router's :class:`~.shadow.ShadowMirror` (None unless
        ``MXNET_TPU_SHADOW`` was on at start) — drills arm it and
        pass it as the ``swap_model`` gate."""
        return self._shadow

    def set_shadow_target(self, target, model_id=None, version=None,
                          fraction=None):
        """Arm the shadow mirror at a candidate seat (an in-process
        engine handle or a ``"host:port"`` wire address). Raises
        :class:`~.queue.ServingError` when shadow validation is off
        (``MXNET_TPU_SHADOW=0``) — arming a mirror that cannot exist
        should be loud, not a silent no-op."""
        if self._shadow is None:
            raise ServingError(
                "shadow validation disabled (MXNET_TPU_SHADOW=0)")
        self._shadow.set_target(target, model_id=model_id,
                                version=version, fraction=fraction)
        return self

    def clear_shadow_target(self):
        if self._shadow is not None:
            self._shadow.clear_target()
        return self

    def shadow_verdict(self):
        """The ``/shadow`` body (None when shadow validation is
        off)."""
        return (self._shadow.verdict()
                if self._shadow is not None else None)

    def incidents_snapshot(self):
        """The fleet ``/incidents`` body: this process's incident
        tracker (the router's own signals + every in-process seat's —
        they share one tracker) merged with each routable remote
        seat's ``/incidents``, deduped by incident id."""
        parts = [(None, _incidents.snapshot())]
        for seat in self._remote_seats():
            parts.append((seat.engine_id, seat.incidents_snapshot()))
        out = _incidents.merge_snapshots(parts)
        out["router_id"] = self.router_id
        return out

    def _canary_targets(self):
        """The canary prober's view of the fleet: every seat
        (routable or NOT — black-box probing of a down seat is how
        recovery is detected), remote seats by URL + advertised wire
        port, in-process seats by handle."""
        with self._lock:
            seats = list(self._seats.values())
        out = []
        for seat in seats:
            # the generation token lets the prober re-pin its TOFU
            # golden when a REPLACEMENT seat reuses an id (new model,
            # new golden — not a forever checksum_mismatch page). The
            # hosted model VERSIONS ride the token too: a live
            # hot-swap (same seat, new weights) legitimately changes
            # the canary's answer, so the golden re-pins instead of
            # paging checksum_mismatch forever
            token = seat.token
            if seat.models:
                token += "@" + ",".join(
                    f"{m}={v}" for m, v in sorted(seat.models.items()))
            t = {"engine_id": seat.engine_id, "kind": seat.kind,
                 "token": token}
            if isinstance(seat, _RemoteSeat):
                t["url"] = seat.base_url
                # advertised (port, REAL engine id) from the health
                # poll: the prober's wire handshake pins the identity
                # so a replacement engine on a recycled port is never
                # probed (and TOFU-goldened) under the old seat's name
                t["wire_port"] = seat._advertised[0]
                t["wire_engine_id"] = seat._advertised[1]
            else:
                t["engine"] = seat._engine
            out.append(t)
        return out

    @property
    def canary(self):
        """The router's :class:`~mxnet_tpu.telemetry.canary.
        CanaryProber` (None when ``MXNET_TPU_CANARY=0`` or before
        ``start``)."""
        return self._canary

    def _remote_submit(self, payload):
        """``POST /submit`` handler (exposition-server thread): admit
        + block for the result, JSON either way — the surface a
        CLIENT-SIDE failover target (``serve_loadgen --router-url
        r1,r2``) drives, mirroring the engine's own handler. Refusals
        carry their class name in ``error_type``; a fleet-down shed
        answers 503 so a dumb load balancer (or the loadgen's url
        list) knows to try the next router."""
        t0 = time.perf_counter()
        try:
            fut = self.submit(payload["tokens"],
                              payload.get("token_types"),
                              deadline_ms=payload.get("deadline_ms"),
                              cid=payload.get("cid"),
                              max_new_tokens=payload.get("max_new_tokens"),
                              eos_id=payload.get("eos_id"),
                              temperature=payload.get("temperature"),
                              top_k=payload.get("top_k"),
                              top_p=payload.get("top_p"),
                              seed=payload.get("seed"),
                              model_id=payload.get("model_id"),
                              tenant=payload.get("tenant"),
                              tenant_class=payload.get("tenant_class"))
        except (ServingError, ValueError, LookupError, TypeError) as e:
            name = type(e).__name__
            status = {"NoEngineAvailableError": 503}.get(
                name, _SUBMIT_ERROR_STATUS.get(name, 400))
            return (status, {"ok": False, "error_type": name,
                             "error": str(e),
                             "router_id": self.router_id})
        timeout_s = payload.get("timeout_s") or self._dispatch_timeout_s
        try:
            out = fut.result(timeout=float(timeout_s))
        except Exception as e:
            name = type(e).__name__
            status = {"NoEngineAvailableError": 503}.get(
                name, _SUBMIT_ERROR_STATUS.get(name, 500))
            return (status, {"ok": False, "error_type": name,
                             "error": str(e), "trace_id": fut.trace_id,
                             "router_id": self.router_id})
        return 200, {"ok": True, "result": np.asarray(out).tolist(),
                     "trace_id": fut.trace_id,
                     "router_id": self.router_id,
                     "router_ms": round(
                         (time.perf_counter() - t0) * 1e3, 3),
                     "cost": getattr(fut, "cost", None),
                     "breakdown": getattr(fut, "breakdown", None)}

    def _healthz(self):
        board = self.scoreboard()
        up = sum(1 for r in board.values() if r["routable"])
        with self._lock:
            queue_depth = len(self._queue)
            ha = self._ha
        return (self.running and up > 0,
                {"router_id": self.router_id, "engines_up": up,
                 "engines_total": len(board),
                 "queue_depth": queue_depth,
                 "ha_port": ha.port if ha is not None else None})

    def expose(self, port=0, host="127.0.0.1"):
        """Start (or return) the router's exposition server: the
        AGGREGATED ``/metrics``, fleet ``/healthz`` (ok while ≥1
        engine is routable), ``/stats`` (scoreboard + counters), the
        merged ``/traces`` + ``/traces/<id>``, the fleet ``/costs``
        cost table, ``/slo`` + ``/alerts`` (fleet objectives + every
        seat's seat-level view), the fleet ``/whyslow`` stage
        attribution table, the fleet ``/capture`` corpus summary (and
        ``/shadow`` verdict while shadow validation is on), and
        ``POST /submit`` so clients
        (e.g. ``serve_loadgen --router-url``) can drive this router
        from another process. Closed by :meth:`stop`."""
        from ..telemetry.expo import TelemetryServer

        with self._lock:
            if self._closed:
                raise EngineStoppedError(
                    "cannot expose telemetry on a stopped router")
            if self._expo is not None:
                return self._expo
            srv = TelemetryServer(healthz_fn=self._healthz,
                                  stats_fn=self.snapshot,
                                  metrics_fn=self.metrics_text,
                                  traces_fn=self.traces_summary,
                                  trace_fn=self.get_trace,
                                  warmup_fn=self.warmup_manifest,
                                  costs_fn=self.cost_table,
                                  submit_fn=self._remote_submit,
                                  slo_fn=self.slo_snapshot,
                                  alerts_fn=self.alerts_snapshot,
                                  incidents_fn=self.incidents_snapshot,
                                  whyslow_fn=self.whyslow,
                                  history_fn=(
                                      self._history.store
                                      if self._history is not None
                                      else None),
                                  capture_fn=self.capture_summary,
                                  shadow_fn=(
                                      self._shadow.verdict
                                      if self._shadow is not None
                                      else None),
                                  port=port, host=host)
            self._expo = srv
            # active/active HA journal listener: rides the exposition
            # lifecycle like the engine's wire listener; the port is
            # advertised in /healthz as ha_port so the PEER discovers
            # it off its health poll — a bind failure degrades to
            # unjournaled HA, never a dead router
            if (self._peer_url
                    or envvars.get("MXNET_TPU_ROUTER_HA_PORT")):
                self._ha_listen(host)
        _events.emit("telemetry_expose", router_id=self.router_id,
                     port=srv.port, host=srv.host)
        return srv
