"""Request queue with admission control for the serving engine.

Admission is where a server earns the right to stay up under heavy
traffic: the queue is BOUNDED (a full queue raises
:class:`QueueFullError` to the caller — backpressure, never unbounded
growth), every request can carry a deadline (expired requests are
rejected with :class:`DeadlineExceededError`, a DISTINCT error, not a
silent drop), and close() fails fast instead of accepting work that
will never run. The reference lineage is MXNet Model Server's bounded
job queue in front of its backend workers.

Since the tenancy subsystem (``serving/tenancy.py``) the queue is
CLASS-AWARE: every request lands in its admission class's deque
(``priority``/``standard``/``best-effort``) and ``poll`` dequeues in
weighted-fair order — each class ``c`` owns a virtual finish time
``vft[c]``; the pop takes the backlogged class with the smallest
``vft`` (ties break toward higher priority) and advances it by
``1/weight[c]``, so sustained contention shares dequeues
weight-proportionally while any lone class runs at full rate. A class
waking from idle catches its ``vft`` up to the queue's virtual time
so it cannot claim a retroactive backlog. Under overload, ``put``
prefers EVICTING the newest request of a lower class over refusing
the arrival (best-effort sheds first, priority last); the evicted
request is returned to the caller, who fails its future loudly. A
single-class workload reduces to the exact pre-tenancy bounded FIFO.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque

import numpy as np

from ..base import MXNetError
from ..telemetry import attribution as _attribution
from ..telemetry import events as _events
from ..telemetry import spans as _spans
from ..telemetry.trace import new_trace_id
from . import tenancy
from .tenancy import UnknownModelError

__all__ = ["ServingError", "QueueFullError", "DeadlineExceededError",
           "RequestTooLongError", "EngineStoppedError",
           "InvalidSamplingError", "UnknownModelError", "InferenceFuture",
           "Request", "RequestQueue", "validate_tokens",
           "validate_sampling"]


class ServingError(MXNetError):
    """Base class for serving-layer failures."""


class QueueFullError(ServingError):
    """Admission refused: the request queue is at max depth
    (backpressure — retry later or shed upstream)."""


class DeadlineExceededError(ServingError):
    """The request's deadline passed before compute finished."""


class RequestTooLongError(ServingError):
    """The request does not fit the largest configured row bucket."""


class EngineStoppedError(ServingError):
    """The engine is stopped (or stopping) and admits no new work."""


class InvalidSamplingError(ServingError):
    """The request's sampling parameters are out of range — refused at
    admission (HTTP 400 / wire error frame), never inside the compiled
    step where a bad ``top_p`` would surface as NaN tokens."""


class InferenceFuture:
    """Single-assignment result slot handed back by ``submit``.

    Minimal on purpose (stdlib ``concurrent.futures.Future`` drags in
    executor/cancel semantics the engine doesn't have): ``result``
    blocks until the worker fulfils it, re-raising the request's
    failure (deadline, shutdown, model error) in the CALLER's thread.

    ``cost`` is the request's amortized bill, written by the engine at
    dispatch (and forwarded by the router across processes): a dict of
    ``engine_id``, row-length ``bucket``, token-share ``device_s`` of
    the batch forward, ``compiled`` (first-visit batch), ``tokens``
    and ``batch_requests`` — None until dispatched (sheds and
    pre-dispatch expiries never ran, so they cost nothing).

    STREAMING: a decode request's future also carries the token
    stream. The engine (or the router/wire relaying for a remote one)
    delivers each generated token with :meth:`push_part`; consumers
    either iterate :meth:`stream` (blocking generator — the client
    shape) or register :meth:`add_part_callback` (the relay shape:
    wire listeners and routers forward parts without a thread per
    request). Parts are ADVISORY latency signal — ``result()`` always
    returns the complete, authoritative output, so a consumer that
    lost parts (killed connection) misses nothing by waiting for the
    final result instead.
    """

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc = None
        self._lock = threading.Lock()
        # one condition over the same lock wakes stream() readers on
        # both new parts and completion
        self._parts_cv = threading.Condition(self._lock)
        self._callbacks = []
        self._parts = []
        # part-callback entries are [fn, cursor] pairs: deliveries are
        # driven by a SINGLE drainer at a time (the _part_draining
        # flag), so every callback sees parts strictly in order even
        # when a registration's replay races fresh pushes from the
        # engine worker — and no lock is ever held across a callback
        self._part_callbacks = []
        self._part_draining = False
        self.cost = None
        # critical-path decomposition of the request's wall time
        # (telemetry.attribution), written by the engine at completion
        # and relayed by router/wire exactly like cost — None until
        # finished (or when attribution is off)
        self.breakdown = None

    def done(self):
        return self._event.is_set()

    def _finish(self, value, exc):
        # first write wins: a batch-failure sweep arriving after a
        # request was already fulfilled must not clobber its result.
        # Callbacks are SNAPSHOT under the lock and invoked OUTSIDE it:
        # a done-callback may block, take other locks, or reentrantly
        # submit/resolve — under the future's lock any of those
        # deadlocks the completing thread (the engine worker) against
        # every other waiter. tools/mxlint's lock-callback rule pins
        # this shape.
        with self._lock:
            if self._event.is_set():
                return
            self._value = value
            self._exc = exc
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
            self._part_callbacks = []
            self._parts_cv.notify_all()
        for cb in callbacks:
            self._run_callback(cb)

    def _run_callback(self, cb):
        try:
            cb(self)
        except Exception as e:
            # a broken observer must not lose the result — but it must
            # not vanish either (thread-hygiene contract)
            _events.emit("future_callback_error",
                         trace_id=getattr(self, "trace_id", None),
                         error=repr(e))

    def set_result(self, value):
        self._finish(value, None)

    def set_exception(self, exc):
        self._finish(None, exc)

    def add_done_callback(self, fn):
        """Call ``fn(self)`` once the future resolves (immediately when
        it already has) — the router's completion hook. ``fn`` runs
        OUTSIDE the future's lock (it may reenter submit); exceptions
        are swallowed after leaving a ``future_callback_error`` event."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        self._run_callback(fn)

    # -- streaming (decode token parts) ------------------------------------
    def _drain_parts(self):
        """Deliver pending parts to registered part callbacks, in
        order, from exactly ONE thread at a time. Callers must have
        set ``_part_draining`` under the lock before calling; the
        drain releases it when no work remains. Callbacks run OUTSIDE
        the lock (same contract as done-callbacks); the single-drainer
        discipline is what keeps a registration's replay from racing a
        fresh push into out-of-order delivery."""
        while True:
            with self._lock:
                work = []
                for entry in self._part_callbacks:
                    cur = entry[1]
                    if cur < len(self._parts):
                        work.append((entry[0], self._parts[cur]))
                        entry[1] = cur + 1
                if not work:
                    self._part_draining = False
                    return
            for fn, part in work:
                try:
                    fn(self, part)
                except Exception as e:
                    _events.emit("future_callback_error",
                                 trace_id=getattr(self, "trace_id",
                                                  None),
                                 error=repr(e))

    def push_part(self, part):
        """Deliver one streamed partial (a generated-token dict).
        Returns False once the future is resolved — late parts from a
        racing completion are dropped, never delivered out of order
        after the final result."""
        with self._lock:
            if self._event.is_set():
                return False
            self._parts.append(part)
            self._parts_cv.notify_all()
            if self._part_draining or not self._part_callbacks:
                return True
            self._part_draining = True
        self._drain_parts()
        return True

    def add_part_callback(self, fn):
        """Call ``fn(self, part)`` for every streamed part — parts
        already received are replayed first (even on a resolved
        future: a relay attached late misses nothing), and replay vs
        concurrent pushes stays strictly ordered (the single-drainer
        discipline above)."""
        with self._lock:
            self._part_callbacks.append([fn, 0])
            if self._part_draining:
                return              # the active drainer picks it up
            self._part_draining = True
        self._drain_parts()

    def parts(self):
        """Snapshot of the parts received so far."""
        with self._lock:
            return list(self._parts)

    def stream(self, timeout=None):
        """Blocking generator over the token parts, ending when the
        future resolves. ``timeout`` bounds each WAIT for the next
        part (inter-token patience), not the whole stream. The
        request's failure — deadline, shutdown, model error — re-
        raises after the received parts have been yielded, exactly as
        ``result()`` would raise it."""
        i = 0
        while True:
            with self._parts_cv:
                while i >= len(self._parts) and not self._event.is_set():
                    if not self._parts_cv.wait(timeout):
                        raise TimeoutError(
                            "no decode token within the stream timeout")
                if i < len(self._parts):
                    part = self._parts[i]
                    i += 1
                else:
                    break               # resolved and fully drained
            yield part
        if self._exc is not None:
            raise self._exc

    def exception(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("inference result not ready")
        return self._exc

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("inference result not ready")
        if self._exc is not None:
            raise self._exc
        return self._value


_req_ids = itertools.count()


def validate_tokens(tokens, token_types):
    """Shared admission validation (engine Request AND router
    RouterRequest): int32-flatten tokens, reject empty, shape-match
    token_types. Returns the normalized ``(tokens, token_types)``."""
    tokens = np.asarray(tokens, np.int32).reshape(-1)
    if tokens.size == 0:
        raise ValueError("empty request")
    if token_types is not None:
        token_types = np.asarray(token_types, np.int32).reshape(-1)
        if token_types.shape != tokens.shape:
            raise ValueError(
                f"token_types length {token_types.size} != tokens "
                f"length {tokens.size}")
    return tokens, token_types


def validate_sampling(temperature=None, top_k=None, top_p=None,
                      seed=None):
    """Shared sampling-parameter admission validation (decode engine
    submit, wire SUBMIT, HTTP ``/submit``, router): range-check and
    normalize, raising :class:`InvalidSamplingError` up front so a bad
    request is a typed 4xx, not a NaN inside the compiled step.
    Returns ``(temperature, top_k, top_p, seed)`` with Nones preserved
    (None means "engine default")."""
    if temperature is not None:
        try:
            temperature = float(temperature)
        except (TypeError, ValueError):
            raise InvalidSamplingError(
                f"temperature must be a number, got {temperature!r}")
        if not np.isfinite(temperature) or temperature < 0.0:
            raise InvalidSamplingError(
                f"temperature must be finite and >= 0, got "
                f"{temperature}")
    if top_k is not None:
        try:
            ok = float(top_k) == int(top_k)
        except (TypeError, ValueError):
            ok = False
        if not ok or int(top_k) < 0:
            raise InvalidSamplingError(
                f"top_k must be an integer >= 0, got {top_k!r}")
        top_k = int(top_k)
    if top_p is not None:
        try:
            top_p = float(top_p)
        except (TypeError, ValueError):
            raise InvalidSamplingError(
                f"top_p must be a number, got {top_p!r}")
        if not np.isfinite(top_p) or not 0.0 < top_p <= 1.0:
            raise InvalidSamplingError(
                f"top_p must be in (0, 1], got {top_p}")
    if seed is not None:
        try:
            ok = float(seed) == int(seed)
        except (TypeError, ValueError):
            ok = False
        if not ok:
            raise InvalidSamplingError(
                f"seed must be an integer, got {seed!r}")
        seed = int(seed) & 0x7FFFFFFF
    return temperature, top_k, top_p, seed


class Request:
    """One queued inference request and its timing breadcrumbs.

    ``trace_id`` is the request's cross-layer identity: minted here (at
    submit time), it follows the request through queue→batcher→dispatch
    via the telemetry contextvar, gets stamped into profiler
    Chrome-trace/xprof spans, and names the request in the structured
    event log — ``id`` stays the cheap in-process ordinal.

    ``span`` is the request's ROOT span (``serving/request``): started
    here, ended by the engine at complete/fail/shed — its duration is
    the tail-sampling input, so only slow/errored/shed requests retain
    their full queue→pack→forward span trees.

    A fronting :class:`~.router.ServingRouter` passes its own
    ``trace_id`` and root-span id down so the engine-side tree parents
    under the router's ``router/request`` span — the same frame-carried
    ``(trace_id, span_id)`` crossing the dist_async wire uses (the
    parent may live in ANOTHER process; ``local_root=True`` keeps the
    engine's tail-sampling decision local either way).
    """

    __slots__ = ("id", "trace_id", "span", "tokens", "token_types",
                 "deadline", "future", "t_submit", "t_drain",
                 "t_dispatch", "t_done", "tenant", "tenant_class",
                 "model_id", "stages", "t_activity", "t_defer",
                 "defers")

    def __init__(self, tokens, token_types=None, deadline_ms=None,
                 trace_id=None, parent_span_id=None, tenant=None,
                 tenant_class=None, model_id=None):
        self.id = next(_req_ids)
        self.trace_id = trace_id or new_trace_id("req")
        self.tokens, self.token_types = validate_tokens(tokens,
                                                        token_types)
        self.tenant = str(tenant) if tenant is not None else None
        self.tenant_class = tenancy.normalize_class(tenant_class)
        self.model_id = str(model_id) if model_id is not None else None
        if deadline_ms is None:
            # per-class deadline budget: under overload, expiry then
            # consumes the short-budget (best-effort) classes first
            deadline_ms = tenancy.class_deadline_ms().get(
                self.tenant_class)
        self.t_submit = time.monotonic()
        self.span = _spans.start_span(
            "serving/request", trace_id=self.trace_id,
            parent_id=parent_span_id,
            attrs={"tokens": int(self.tokens.size)}, local_root=True)
        self.deadline = (self.t_submit + deadline_ms / 1e3
                         if deadline_ms is not None else None)
        self.future = InferenceFuture()
        # clients hold only the future; mirror the id there so caller
        # logs can name the request the server's telemetry names
        self.future.trace_id = self.trace_id
        self.t_drain = self.t_dispatch = self.t_done = None
        # stage-attribution breadcrumbs (telemetry.attribution.stamp):
        # (stage, t0, t1) monotonic tuples; None = attribution off, the
        # whole subsystem then costs one attribute check per stamp site
        self.stages = [] if _attribution.enabled() else None
        self.t_activity = None      # end of the last stamped stage
        self.t_defer = None         # first KV page-exhaustion defer
        self.defers = 0

    def __len__(self):
        return int(self.tokens.size)

    def expired(self, now=None):
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                > self.deadline)


class RequestQueue:
    """Thread-safe bounded admission queue the continuous batcher
    drains in weighted-fair class order.

    ``put`` never blocks and never grows past ``max_depth``; under
    overload it sheds DOWNWARD — a higher-class arrival evicts the
    newest request of the lowest backlogged class below it (returned
    to the caller to fail loudly), and only an arrival with nobody
    beneath it eats :class:`QueueFullError` (that IS the flow
    control). Per-class depth budgets (fractions of ``max_depth``)
    bound each class before the global bound is even reached.
    ``poll`` is the iteration-level drain: wait up to ``timeout`` for
    the queue to become non-empty, then take everything available (up
    to ``max_items``) WITHOUT waiting for stragglers — the Orca-style
    continuous-batching discipline (batch what is there, never hold a
    batch open for latecomers) — in WFQ order, so the batcher's
    first-fit packing draws weight-proportionally from the classes.

    The WFQ state machine is deliberately deterministic (no wall
    clock): ``vft[c]`` floats advanced by exact ``1/weight`` steps,
    ties broken by class priority — tests/test_tenancy.py pins exact
    dequeue orders as goldens.
    """

    def __init__(self, max_depth=256, class_weights=None,
                 depth_shares=None):
        if max_depth < 1:
            raise ValueError("max_depth must be >= 1")
        self._max_depth = max_depth
        weights = dict(class_weights if class_weights is not None
                       else tenancy.class_weights())
        shares = dict(depth_shares if depth_shares is not None
                      else tenancy.class_depth_shares())
        self._weights = {c: float(weights.get(c, 1.0))
                         for c in tenancy.TENANT_CLASSES}
        self._budget = {
            c: max(1, int(round(max_depth * float(shares.get(c, 1.0)))))
            for c in tenancy.TENANT_CLASSES}
        self._dqs = {c: deque() for c in tenancy.TENANT_CLASSES}
        self._vft = {c: 0.0 for c in tenancy.TENANT_CLASSES}
        self._vtime = 0.0
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = False

    def __len__(self):
        with self._lock:
            return sum(len(dq) for dq in self._dqs.values())

    @property
    def max_depth(self):
        return self._max_depth

    @property
    def closed(self):
        with self._lock:
            return self._closed

    def depths(self):
        """Per-class queue depth ``{class: n}`` — the WFQ split the
        ``/stats`` body, flight-bundle scheduler sections and the
        ``mxnet_tpu_serving_wfq_queue_depth`` gauge expose."""
        with self._lock:
            return {c: len(dq) for c, dq in self._dqs.items()}

    def _class_of(self, request):
        cls = getattr(request, "tenant_class", None)
        return cls if cls in self._dqs else "standard"

    def _evict_locked(self, above):
        """Pop the NEWEST request of the lowest-priority backlogged
        class strictly below ``above`` (None when nothing beneath it
        can be shed)."""
        idx = tenancy.TENANT_CLASSES.index(above)
        for cls in reversed(tenancy.TENANT_CLASSES[idx + 1:]):
            if self._dqs[cls]:
                return self._dqs[cls].pop()
        return None

    def put(self, request):
        """Admit ``request``; returns the lower-class victim it
        EVICTED under overload (None normally) — the caller fails the
        victim's future and counts the shed."""
        with self._lock:
            if self._closed:
                raise EngineStoppedError(
                    "serving engine is stopped; request refused")
            cls = self._class_of(request)
            dq = self._dqs[cls]
            if len(dq) >= self._budget[cls]:
                raise QueueFullError(
                    f"request queue full for class {cls} (budget "
                    f"{self._budget[cls]} of depth {self._max_depth}); "
                    "backpressure — retry later")
            victim = None
            if sum(len(d) for d in self._dqs.values()) \
                    >= self._max_depth:
                victim = self._evict_locked(cls)
                if victim is None:
                    raise QueueFullError(
                        f"request queue full (depth {self._max_depth}); "
                        "backpressure — retry later")
            if not dq:
                # waking from idle: catch up to the queue's virtual
                # time — an idle class must not bank credit
                self._vft[cls] = max(self._vft[cls], self._vtime)
            dq.append(request)
            self._not_empty.notify()
            return victim

    def _pop_locked(self):
        backlogged = [c for c in tenancy.TENANT_CLASSES
                      if self._dqs[c]]
        if not backlogged:
            return None
        # min virtual finish; ties go to the higher-priority class
        # (TENANT_CLASSES order) — deterministic for the goldens
        cls = min(backlogged,
                  key=lambda c: (self._vft[c],
                                 tenancy.TENANT_CLASSES.index(c)))
        self._vtime = self._vft[cls]
        self._vft[cls] += 1.0 / self._weights[cls]
        return self._dqs[cls].popleft()

    def poll(self, max_items, timeout=0.0):
        """Drain up to ``max_items`` requests in WFQ order; block up
        to ``timeout`` seconds only while the queue is empty."""
        deadline = time.monotonic() + timeout
        with self._not_empty:
            while not any(self._dqs.values()) and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._not_empty.wait(remaining):
                    break
            out = []
            while len(out) < max_items:
                r = self._pop_locked()
                if r is None:
                    break
                out.append(r)
            now = time.monotonic()
            for r in out:
                first = r.t_drain is None
                r.t_drain = now
                # first drain only: a requeued (KV-deferred) request's
                # second wait is the DEFER episode, stamped by the
                # decode engine when the re-admit finally lands
                if first and r.stages is not None:
                    _attribution.stamp(
                        r, "wfq_wait", r.t_submit, now,
                        attrs={"tenant_class": r.tenant_class})
            return out

    def requeue(self, request):
        """Put an already-admitted request back at the FRONT of its
        class (the decode engine defers a join when the KV page pool
        is momentarily exhausted). Bypasses the depth bound — the
        request was admitted once and must not be shed for coming
        back — and rewinds the class's virtual finish so the carry is
        immediately eligible again."""
        with self._lock:
            cls = self._class_of(request)
            self._dqs[cls].appendleft(request)
            self._vft[cls] = min(self._vft[cls], self._vtime)
            self._not_empty.notify()

    def close(self):
        """Refuse new work; queued requests stay drainable (the engine
        decides whether to run or fail them)."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()

    def drain_all(self):
        """Take every queued request (shutdown path), priority class
        first, FIFO within a class."""
        with self._lock:
            out = []
            for cls in tenancy.TENANT_CLASSES:
                out.extend(self._dqs[cls])
                self._dqs[cls].clear()
            return out
