"""Serving observability: latency summaries, counters, packing stats.

The reference lineage (MXNet Model Server) exported per-request
latency/queue metrics over its management API; here the same surface
is an in-process stats dict (``ServingStats.snapshot``) plus
``profiler.py`` scopes around the hot stages, so an xprof/Chrome trace
of a serving run shows queue/pack/compute spans next to the device
timeline.

Everything is thread-safe: client threads observe submit/reject
counters while the single worker thread observes batch/compute stats.
"""
from __future__ import annotations

import threading
from collections import deque

__all__ = ["LatencySummary", "ServingStats", "nearest_rank"]


def nearest_rank(sorted_xs, p):
    """Nearest-rank percentile of an ascending-sorted sample (None on
    empty) — THE percentile convention for every serving metric
    (engine-side summaries and the loadgen's client-observed numbers
    share it so the two can be compared directly)."""
    if not sorted_xs:
        return None
    rank = max(0, min(len(sorted_xs) - 1,
                      int(round(p / 100.0 * len(sorted_xs))) - 1))
    return sorted_xs[rank]


class LatencySummary:
    """Bounded-window latency aggregator (milliseconds).

    Keeps a ring of the most recent ``capacity`` observations for
    percentiles (a serving process runs forever; unbounded sample
    lists would not) plus running count/sum/max over the full
    lifetime. p50/p95/p99 therefore describe the recent window, count
    and mean the whole run — the usual server-metrics convention.
    """

    def __init__(self, capacity=4096):
        self._window = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._max = 0.0

    def observe(self, ms):
        with self._lock:
            self._window.append(float(ms))
            self._count += 1
            self._total += ms
            if ms > self._max:
                self._max = ms

    @property
    def count(self):
        return self._count

    def percentile(self, p):
        """Nearest-rank percentile over the recent window (None when
        nothing was observed)."""
        with self._lock:
            xs = sorted(self._window)
        return nearest_rank(xs, p)

    def snapshot(self):
        with self._lock:
            xs = sorted(self._window)
            count, total, mx = self._count, self._total, self._max
        if not xs:
            return {"count": 0}
        return {"count": count,
                "mean_ms": round(total / count, 3),
                "p50_ms": round(nearest_rank(xs, 50), 3),
                "p95_ms": round(nearest_rank(xs, 95), 3),
                "p99_ms": round(nearest_rank(xs, 99), 3),
                "max_ms": round(mx, 3)}


class ServingStats:
    """Counter/gauge/latency bundle for one :class:`ServingEngine`.

    Counters follow the admission-control outcomes one-to-one so a
    dashboard can account for every submitted request:
    ``submitted == completed + failed + rejected_* + expired +
    cancelled + in flight``.
    """

    COUNTERS = ("submitted", "completed", "failed", "rejected_queue_full",
                "rejected_too_long", "rejected_stopped", "expired",
                "cancelled", "batches", "compiles")

    def __init__(self, window=4096):
        self._lock = threading.Lock()
        self._c = {name: 0 for name in self.COUNTERS}
        # dispatched slot accounting for the aggregate packing number
        self._slots = 0
        self._valid_tokens = 0
        self.queue_ms = LatencySummary(window)
        self.pack_ms = LatencySummary(window)
        self.compute_ms = LatencySummary(window)
        self.compile_ms = LatencySummary(window)
        self.total_ms = LatencySummary(window)
        self.batch_requests = LatencySummary(window)   # requests/batch
        self._queue_depth_fn = None
        self._last_batch = None

    def bump(self, name, n=1):
        with self._lock:
            self._c[name] += n

    def count(self, name):
        with self._lock:
            return self._c[name]

    def set_queue_depth_fn(self, fn):
        self._queue_depth_fn = fn

    def observe_batch(self, rows, row_len, valid_tokens, n_requests,
                      bucket_len):
        with self._lock:
            self._c["batches"] += 1
            self._slots += rows * row_len
            self._valid_tokens += valid_tokens
            self._last_batch = {
                "rows": rows, "row_len": row_len, "requests": n_requests,
                "bucket_len": bucket_len,
                "packing_efficiency":
                    round(valid_tokens / float(rows * row_len), 4)}
        self.batch_requests.observe(n_requests)

    def packing_efficiency(self):
        """Aggregate fraction of dispatched slots holding real tokens
        (dummy pad rows from row-count quantization included — the
        honest number the chip actually paid for)."""
        with self._lock:
            if not self._slots:
                return None
            return self._valid_tokens / float(self._slots)

    def snapshot(self):
        with self._lock:
            counters = dict(self._c)
            slots, valid = self._slots, self._valid_tokens
            last = dict(self._last_batch) if self._last_batch else None
        out = {"counters": counters,
               "queue_depth": (self._queue_depth_fn()
                               if self._queue_depth_fn else None),
               "latency": {"queue": self.queue_ms.snapshot(),
                           "pack": self.pack_ms.snapshot(),
                           "compute": self.compute_ms.snapshot(),
                           "compile": self.compile_ms.snapshot(),
                           "total": self.total_ms.snapshot()},
               "dispatched_slots": slots,
               "valid_tokens": valid,
               "packing_efficiency":
                   round(valid / float(slots), 4) if slots else None,
               "last_batch": last}
        return out
