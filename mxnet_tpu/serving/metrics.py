"""Serving observability: latency summaries, counters, packing stats.

The reference lineage (MXNet Model Server) exported per-request
latency/queue metrics over its management API; here the same surface
is an in-process stats dict (``ServingStats.snapshot``) plus
``profiler.py`` scopes around the hot stages, so an xprof/Chrome trace
of a serving run shows queue/pack/compute spans next to the device
timeline.

Since the telemetry subsystem landed, every ``ServingStats`` also
BRIDGES onto the process-wide :data:`mxnet_tpu.telemetry.REGISTRY`:
counters feed ``mxnet_tpu_serving_requests_total{engine_id=..,event=..}``,
each latency summary co-observes a
``mxnet_tpu_serving_latency_ms{engine_id=..,stage=..}`` histogram,
queue depth is a pull gauge, and per-bucket batch traffic lands in
``mxnet_tpu_serving_batch_{tokens,slots}_total{engine_id=..,bucket=..}``.
Every serving family carries an ``engine_id`` label (the ROADMAP
"per-chip router metrics" item): N engines in one process — or N
engine processes scrape-merged at a :class:`~.router.ServingRouter` —
keep disjoint counter children instead of double-counting one
unlabeled set. Registry counters are process-cumulative by Prometheus
contract: ``ServingEngine.reset_stats`` swaps the WINDOW (this
object) while the registry keeps counting — scrapers diff between
scrapes.

Everything is thread-safe: client threads observe submit/reject
counters while the single worker thread observes batch/compute stats.
"""
from __future__ import annotations

import threading
from collections import deque

from .. import envvars
from ..telemetry import spans as _spans
from ..telemetry.registry import REGISTRY

__all__ = ["LatencySummary", "ServingStats", "CostLedger",
           "DispatchOverhead", "DecodeStats", "nearest_rank",
           "merge_cost_buckets", "exemplar_gate", "slow_exemplar",
           "wire_frames_counter", "wire_bytes_counter",
           "wire_connections_gauge", "wire_refusals_counter",
           "wire_fallback_counter"]


def exemplar_gate():
    """Resolve the latency-exemplar recording gate once per owner
    (engine/router construction): exemplars only make sense when the
    SLO engine runs AND spans are enabled — an exemplar whose trace
    tail sampling can never keep would be a dead link."""
    return bool(envvars.get("MXNET_TPU_SLO")
                and envvars.get("MXNET_TPU_SLO_EXEMPLARS")
                and _spans.enabled())


def slow_exemplar(trace_id, total_ms, gated):
    """The exemplar to attach to a total-latency observation: the
    request's trace id when the gate is open and the request is slow
    enough that tail sampling KEEPS its trace (same threshold), else
    None. The one place the exemplar↔retrievable-trace contract
    lives — engine and router both call it."""
    return (trace_id if gated and total_ms >= _spans.RECORDER.slow_ms
            else None)

# batch-size histogram boundaries (requests per dispatched batch)
_BATCH_REQ_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)

# dispatch-overhead boundaries (ms): the binary wire's round trip minus
# engine time is sub-millisecond on loopback — the default ms buckets
# would fold every sample into the first bucket
_WIRE_OVERHEAD_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                          25.0, 50.0, 100.0, 250.0, 1000.0)


# -- dispatch-wire metric families ------------------------------------------
# Declared HERE once (one label set per family — the mxlint
# telemetry-consistency contract) and shared by serving/wire.py (both
# sides of the binary transport) and serving/router.py (the HTTP/JSON
# fallback path's byte/fallback accounting).

def wire_frames_counter(registry=None):
    reg = registry if registry is not None else REGISTRY
    return reg.counter(
        "mxnet_tpu_wire_frames_total",
        "dispatch-wire frames by side (router/engine), direction and "
        "frame type", ("side", "direction", "frame"))


def wire_bytes_counter(registry=None):
    reg = registry if registry is not None else REGISTRY
    return reg.counter(
        "mxnet_tpu_wire_bytes_total",
        "serialized dispatch payload bytes by side, transport "
        "(wire = binary frames, json = the HTTP fallback bodies) and "
        "direction", ("side", "transport", "direction"))


def wire_connections_gauge(registry=None):
    reg = registry if registry is not None else REGISTRY
    return reg.gauge(
        "mxnet_tpu_wire_connections",
        "live persistent dispatch-wire connections, per side",
        ("side",))


def wire_refusals_counter(registry=None):
    reg = registry if registry is not None else REGISTRY
    return reg.counter(
        "mxnet_tpu_wire_refusals_total",
        "hostile/malformed dispatch-wire frames refused (the frame or "
        "connection errored; the process never does)", ("side",))


def wire_fallback_counter(registry=None):
    reg = registry if registry is not None else REGISTRY
    return reg.counter(
        "mxnet_tpu_wire_fallback_total",
        "remote dispatches a wire-capable router sent over the "
        "HTTP/JSON path instead (peer advertises no wire port, or its "
        "wire connections are down), per engine", ("engine_id",))


class DispatchOverhead:
    """Router-observed remote dispatch overhead by transport: the full
    dispatch round trip MINUS the engine-observed serving wall
    (``engine_ms`` in the reply) — i.e. what serialization, transport
    and demux cost on top of the model. This is THE wire-vs-JSON
    comparison number; each sample co-observes a registry histogram
    (fine sub-ms buckets) and a per-transport :class:`LatencySummary`
    for exact window percentiles in the router snapshot."""

    def __init__(self, registry=None):
        reg = registry if registry is not None else REGISTRY
        self._hist = reg.histogram(
            "mxnet_tpu_wire_dispatch_overhead_ms",
            "remote dispatch round trip minus engine-observed serving "
            "wall, by transport", ("transport",),
            buckets=_WIRE_OVERHEAD_BUCKETS)
        self._summaries = {}
        self._lock = threading.Lock()

    def observe(self, transport, ms):
        transport = str(transport)
        summary = self._summaries.get(transport)
        if summary is None:
            with self._lock:
                summary = self._summaries.setdefault(
                    transport, LatencySummary(
                        4096, self._hist.labels(transport=transport)))
        summary.observe(max(0.0, float(ms)))

    def snapshot(self):
        with self._lock:
            items = list(self._summaries.items())
        return {t: s.snapshot() for t, s in items}


# inter-token latency boundaries (ms): steady-state decode iterations
# are model-forward-sized — finer than the default request buckets,
# coarser than the wire-overhead ones
_INTER_TOKEN_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                        500.0, 1000.0, 2500.0, 10000.0)


class DecodeStats:
    """Decode-loop observability bundle for one ``DecodeEngine`` —
    the token-level numbers the request-level :class:`ServingStats`
    has no axis for: inter-token latency (THE decode SLI — the default
    ``decode_inter_token`` LatencySLO judges its histogram), time to
    first token, generated-token throughput, and slot churn
    (join/leave events at iteration boundaries). KV-page occupancy
    lives on the pool's own gauges (``serving/kvcache.py``)."""

    def __init__(self, engine_id, window=4096, registry=None):
        reg = registry if registry is not None else REGISTRY
        self.engine_id = str(engine_id)
        self.window = window          # public: reset_stats reads this
        eid = self.engine_id
        self.inter_token_ms = LatencySummary(
            window, reg.histogram(
                "mxnet_tpu_serving_inter_token_latency_ms",
                "wall time between consecutive generated tokens of one "
                "sequence (the decode-path SLI), per engine",
                ("engine_id",), buckets=_INTER_TOKEN_BUCKETS)
            .labels(engine_id=eid))
        self.ttft_ms = LatencySummary(
            window, reg.histogram(
                "mxnet_tpu_serving_ttft_ms",
                "time to first token: submit to the prefill's first "
                "generated token, per engine", ("engine_id",))
            .labels(engine_id=eid))
        self._c_tokens = reg.counter(
            "mxnet_tpu_serving_decode_tokens_total",
            "generated tokens, per engine", ("engine_id",)) \
            .labels(engine_id=eid)
        self._c_iters = reg.counter(
            "mxnet_tpu_serving_decode_iterations_total",
            "decode-loop iterations dispatched, per engine",
            ("engine_id",)).labels(engine_id=eid)
        slot = reg.counter(
            "mxnet_tpu_serving_decode_slot_events_total",
            "decode-batch slot churn: sequences joining at an "
            "iteration boundary and leaving on EOS/max-tokens, per "
            "engine", ("engine_id", "event"))
        self._c_join = slot.labels(engine_id=eid, event="join")
        self._c_leave = slot.labels(engine_id=eid, event="leave")
        self._c_chunks = reg.counter(
            "mxnet_tpu_serving_decode_prefill_chunks_total",
            "chunked-prefill steps interleaved at decode iteration "
            "boundaries (rate vs decode_iterations_total = the share "
            "of loop turns spent prefilling), per engine",
            ("engine_id",)).labels(engine_id=eid)
        self._q_split = reg.gauge(
            "mxnet_tpu_serving_decode_queue_split",
            "decode scheduler population by phase: requests waiting "
            "for prefill vs sequences in the decode batch, per engine",
            ("engine_id", "phase"))
        self._lock = threading.Lock()
        self._tokens = 0
        self._iters = 0
        self._joins = 0
        self._leaves = 0
        self._slot_steps = 0      # rows dispatched across iterations
        self._active_steps = 0    # live rows among them (utilization)
        self._chunks = 0
        self._chunk_tokens = 0

    def set_split_fns(self, prefill_fn, decode_fn):
        """Wire the phase-split pull gauges (scrape-time reads)."""
        self._q_split.labels(engine_id=self.engine_id,
                             phase="prefill").set_function(prefill_fn)
        self._q_split.labels(engine_id=self.engine_id,
                             phase="decode").set_function(decode_fn)

    def observe_token(self, n=1):
        """One generated token (prefill's first token and every
        iteration token land here, at emission)."""
        with self._lock:
            self._tokens += n
        self._c_tokens.inc(n)

    def observe_iteration(self, rows, active):
        with self._lock:
            self._iters += 1
            self._slot_steps += rows
            self._active_steps += active
        self._c_iters.inc()

    def observe_join(self, n=1):
        with self._lock:
            self._joins += n
        self._c_join.inc(n)

    def observe_leave(self, n=1):
        with self._lock:
            self._leaves += n
        self._c_leave.inc(n)

    def observe_chunk(self, tokens):
        """One chunked-prefill step (``tokens`` real prompt tokens)
        interleaved at an iteration boundary."""
        with self._lock:
            self._chunks += 1
            self._chunk_tokens += tokens
        self._c_chunks.inc()

    def snapshot(self):
        with self._lock:
            out = {"tokens": self._tokens, "iterations": self._iters,
                   "joins": self._joins, "leaves": self._leaves,
                   "prefill_chunks": self._chunks,
                   "prefill_chunk_tokens": self._chunk_tokens,
                   "slot_utilization": (
                       round(self._active_steps / self._slot_steps, 4)
                       if self._slot_steps else None)}
        out["inter_token"] = self.inter_token_ms.snapshot()
        out["ttft"] = self.ttft_ms.snapshot()
        return out


def nearest_rank(sorted_xs, p):
    """Nearest-rank percentile of an ascending-sorted sample (None on
    empty) — THE percentile convention for every serving metric
    (engine-side summaries and the loadgen's client-observed numbers
    share it so the two can be compared directly)."""
    if not sorted_xs:
        return None
    rank = max(0, min(len(sorted_xs) - 1,
                      int(round(p / 100.0 * len(sorted_xs))) - 1))
    return sorted_xs[rank]


class LatencySummary:
    """Bounded-window latency aggregator (milliseconds).

    Keeps a ring of the most recent ``capacity`` observations for
    percentiles (a serving process runs forever; unbounded sample
    lists would not) plus running count/sum/max over the full
    lifetime. p50/p95/p99 therefore describe the recent window, count
    and mean the whole run — the usual server-metrics convention.

    ``hist`` (optional) is a telemetry histogram child co-observed on
    every sample, so the same numbers are scrapeable at /metrics.
    ``exemplar`` (a trace id) rides through to the histogram as an
    OpenMetrics exemplar — the machine link from a latency bucket back
    to a retrievable trace at ``/traces/<id>``.
    """

    def __init__(self, capacity=4096, hist=None):
        self._window = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._max = 0.0
        self._hist = hist

    def observe(self, ms, exemplar=None):
        with self._lock:
            self._window.append(float(ms))
            self._count += 1
            self._total += ms
            if ms > self._max:
                self._max = ms
        if self._hist is not None:
            self._hist.observe(ms, exemplar=exemplar)

    @property
    def count(self):
        return self._count

    def percentile(self, p):
        """Nearest-rank percentile over the recent window (None when
        nothing was observed)."""
        with self._lock:
            xs = sorted(self._window)
        return nearest_rank(xs, p)

    def snapshot(self):
        with self._lock:
            xs = sorted(self._window)
            count, total, mx = self._count, self._total, self._max
        if not xs:
            return {"count": 0}
        return {"count": count,
                "mean_ms": round(total / count, 3),
                "p50_ms": round(nearest_rank(xs, 50), 3),
                "p95_ms": round(nearest_rank(xs, 95), 3),
                "p99_ms": round(nearest_rank(xs, 99), 3),
                "max_ms": round(mx, 3)}


class CostLedger:
    """Per-bucket resource/cost accounting for one engine — what a
    request actually COSTS, not just how fast it was.

    Every dispatched batch lands in the row-length bucket it ran at,
    split by where the wall time went:

    - ``device_s``   — forward wall seconds of memory-hit batches (the
      steady-state serving cost);
    - ``compile_s``  — first-visit trace+compile wall seconds (live
      batches AND warmup replays — the amortizable startup cost);
    - ``warmup_s``   — memory-hit warmup forwards (dummy traffic; kept
      apart so device_s reconciles against real requests exactly);
    - ``request_s``  — the amortizable slice: seconds of batches that
      carried real requests (device or compile). The engine writes
      each member request's token-weighted share onto its
      ``InferenceFuture.cost``, so ``sum(per-request device_s) ==
      request_s`` by construction — the exactness contract
      tests/test_profiling.py pins and ``serve_loadgen`` cross-checks;
    - ``requests`` / ``valid_tokens`` / ``batches`` — the divisor side.

    The same numbers feed the ``mxnet_tpu_serving_cost_*`` registry
    families (engine-labeled, per the fleet contract) so Prometheus
    rates give fleet cost-per-1k-tokens live. The ledger is
    process-cumulative like registry counters: ``reset_stats`` swaps
    the stats WINDOW, never the ledger — scrapers diff ``/costs``
    between scrapes.
    """

    FIELDS = ("device_s", "compile_s", "warmup_s", "request_s")

    def __init__(self, engine_id, registry=None):
        reg = registry if registry is not None else REGISTRY
        self.engine_id = str(engine_id)
        self._lock = threading.Lock()
        self._buckets = {}      # bucket_len -> row dict
        self._sec = reg.counter(
            "mxnet_tpu_serving_cost_seconds_total",
            "accumulated serving wall seconds by row-length bucket and "
            "kind (device = memory-hit batch forward, compile = "
            "first-visit trace+compile, warmup = dummy warmup forward)",
            ("engine_id", "bucket", "kind"))
        self._req = reg.counter(
            "mxnet_tpu_serving_cost_requests_total",
            "requests whose device time was amortized into the cost "
            "ledger, by row-length bucket",
            ("engine_id", "bucket"))
        self._tok = reg.counter(
            "mxnet_tpu_serving_cost_tokens_total",
            "valid tokens cost-accounted, by row-length bucket",
            ("engine_id", "bucket"))

    def _row(self, bucket_len):
        row = self._buckets.get(bucket_len)
        if row is None:
            row = self._buckets.setdefault(
                bucket_len, {f: 0.0 for f in self.FIELDS}
                | {"requests": 0, "valid_tokens": 0, "batches": 0})
        return row

    def observe_batch(self, bucket_len, seconds, requests, valid_tokens,
                      compiled):
        """One LIVE dispatched batch: ``seconds`` is the batch's
        forward wall (including the compile on first visit)."""
        kind = "compile" if compiled else "device"
        with self._lock:
            row = self._row(bucket_len)
            row["compile_s" if compiled else "device_s"] += seconds
            if requests:
                row["request_s"] += seconds
            row["requests"] += requests
            row["valid_tokens"] += valid_tokens
            row["batches"] += 1
        self._sec.labels(engine_id=self.engine_id, bucket=bucket_len,
                         kind=kind).inc(seconds)
        if requests:
            self._req.labels(engine_id=self.engine_id,
                             bucket=bucket_len).inc(requests)
        if valid_tokens:
            self._tok.labels(engine_id=self.engine_id,
                             bucket=bucket_len).inc(valid_tokens)

    def observe_decode(self, rows_bucket, seconds, tokens, completed,
                       compiled):
        """One decode-loop iteration, keyed by the NEGATED rows bucket
        (decode batches have no row length; the sign keeps the decode
        key space disjoint from prefill prompt-length buckets even
        when ``max_rows`` overlaps a bucket value — ``-8`` reads as "a
        decode batch of 8 rows"). Every iteration carries live
        requests by construction, so its wall lands in ``request_s`` —
        the engine amortizes the same seconds across the member
        sequences' bills, keeping the sum(bills) == request_s
        exactness contract. ``completed`` counts the sequences that
        FINISHED this iteration (requests are counted once, at leave,
        not once per token)."""
        kind = "compile" if compiled else "device"
        with self._lock:
            row = self._row(rows_bucket)
            row["compile_s" if compiled else "device_s"] += seconds
            row["request_s"] += seconds
            row["requests"] += completed
            row["valid_tokens"] += tokens
            row["batches"] += 1
        self._sec.labels(engine_id=self.engine_id, bucket=rows_bucket,
                         kind=kind).inc(seconds)
        if completed:
            self._req.labels(engine_id=self.engine_id,
                             bucket=rows_bucket).inc(completed)
        if tokens:
            self._tok.labels(engine_id=self.engine_id,
                             bucket=rows_bucket).inc(tokens)

    def observe_warmup(self, bucket_len, seconds, compiled):
        """A dummy warmup forward (no requests): compile seconds count
        with the compiles, memory-hit replays stay in warmup_s."""
        kind = "compile" if compiled else "warmup"
        with self._lock:
            row = self._row(bucket_len)
            row["compile_s" if compiled else "warmup_s"] += seconds
            row["batches"] += 1
        self._sec.labels(engine_id=self.engine_id, bucket=bucket_len,
                         kind=kind).inc(seconds)

    @staticmethod
    def _derive(row):
        out = dict(row)
        for f in CostLedger.FIELDS:
            out[f] = round(out[f], 6)
        if out["requests"]:
            out["device_ms_per_request"] = round(
                out["request_s"] * 1e3 / out["requests"], 3)
        if out["valid_tokens"]:
            out["device_s_per_1k_tokens"] = round(
                out["request_s"] * 1e3 / out["valid_tokens"], 6)
        return out

    def table(self):
        """``{bucket_len(str): row}`` with derived per-request /
        per-1k-token rates — the ``/costs`` body."""
        with self._lock:
            rows = {str(b): dict(r)
                    for b, r in sorted(self._buckets.items())}
        return {b: self._derive(r) for b, r in rows.items()}

    def totals(self):
        """One row summed across buckets (the /stats `costs` line)."""
        with self._lock:
            rows = [dict(r) for r in self._buckets.values()]
        return self._derive(merge_cost_buckets(rows))


def merge_cost_buckets(rows):
    """Sum cost-ledger rows field-by-field (a router folding N
    engines' buckets, or totals across buckets)."""
    out = {f: 0.0 for f in CostLedger.FIELDS} \
        | {"requests": 0, "valid_tokens": 0, "batches": 0}
    for row in rows:
        for f in CostLedger.FIELDS:
            out[f] += row.get(f, 0.0) or 0.0
        for f in ("requests", "valid_tokens", "batches"):
            out[f] += int(row.get(f, 0) or 0)
    return out


class ServingStats:
    """Counter/gauge/latency bundle for one :class:`ServingEngine`.

    Counters follow the admission-control outcomes one-to-one so a
    dashboard can account for every submitted request:
    ``submitted == completed + failed + rejected_* + expired +
    cancelled + in flight``.
    """

    COUNTERS = ("submitted", "completed", "failed", "rejected_queue_full",
                "rejected_too_long", "rejected_stopped",
                "rejected_unknown_model", "expired",
                "cancelled", "batches", "compiles")

    def __init__(self, window=4096, registry=None, engine_id="default"):
        reg = registry if registry is not None else REGISTRY
        self.window = window          # public: reset_stats reads this
        self.engine_id = str(engine_id)
        eid = self.engine_id
        self._lock = threading.Lock()
        self._c = {name: 0 for name in self.COUNTERS}
        # dispatched slot accounting for the aggregate packing number
        self._slots = 0
        self._valid_tokens = 0
        # registry bridge: children resolved ONCE here so the hot path
        # pays a dict lookup + locked add, never family bookkeeping
        req_total = reg.counter(
            "mxnet_tpu_serving_requests_total",
            "serving requests by admission/completion outcome, per engine",
            ("engine_id", "event"))
        self._reg_c = {name: req_total.labels(engine_id=eid, event=name)
                       for name in self.COUNTERS
                       if name not in ("batches", "compiles")}
        # not request outcomes — their own families keep the
        # requests_total label space reconcilable request-for-request
        self._reg_c["batches"] = reg.counter(
            "mxnet_tpu_serving_batches_total",
            "dispatched packed batches, per engine",
            ("engine_id",)).labels(engine_id=eid)
        self._reg_c["compiles"] = reg.counter(
            "mxnet_tpu_serving_compiles_total",
            "first-visit shape trace+compiles, per engine",
            ("engine_id",)).labels(engine_id=eid)
        lat = reg.histogram("mxnet_tpu_serving_latency_ms",
                            "serving latency by pipeline stage, per engine",
                            ("engine_id", "stage"))
        self.queue_ms = LatencySummary(
            window, lat.labels(engine_id=eid, stage="queue"))
        self.pack_ms = LatencySummary(
            window, lat.labels(engine_id=eid, stage="pack"))
        self.compute_ms = LatencySummary(
            window, lat.labels(engine_id=eid, stage="compute"))
        self.compile_ms = LatencySummary(
            window, lat.labels(engine_id=eid, stage="compile"))
        self.total_ms = LatencySummary(
            window, lat.labels(engine_id=eid, stage="total"))
        self.batch_requests = LatencySummary(
            window, reg.histogram("mxnet_tpu_serving_batch_requests",
                                  "requests per dispatched batch",
                                  ("engine_id",),
                                  buckets=_BATCH_REQ_BUCKETS)
            .labels(engine_id=eid))
        self._reg_batch_tokens = reg.counter(
            "mxnet_tpu_serving_batch_tokens_total",
            "valid tokens dispatched, by row-length bucket",
            ("engine_id", "bucket"))
        self._reg_batch_slots = reg.counter(
            "mxnet_tpu_serving_batch_slots_total",
            "padded slots dispatched, by row-length bucket",
            ("engine_id", "bucket"))
        self._reg_queue_depth = reg.gauge(
            "mxnet_tpu_serving_queue_depth",
            "requests waiting in the admission queue, per engine",
            ("engine_id",)).labels(engine_id=eid)
        self._queue_depth_fn = None
        self._last_batch = None

    def bump(self, name, n=1):
        with self._lock:
            self._c[name] += n
        self._reg_c[name].inc(n)

    def count(self, name):
        with self._lock:
            return self._c[name]

    def set_queue_depth_fn(self, fn):
        self._queue_depth_fn = fn
        # pull gauge: evaluated at scrape time, zero hot-path cost
        self._reg_queue_depth.set_function(fn)

    def observe_batch(self, rows, row_len, valid_tokens, n_requests,
                      bucket_len):
        with self._lock:
            self._c["batches"] += 1
            self._slots += rows * row_len
            self._valid_tokens += valid_tokens
            self._last_batch = {
                "rows": rows, "row_len": row_len, "requests": n_requests,
                "bucket_len": bucket_len,
                "packing_efficiency":
                    round(valid_tokens / float(rows * row_len), 4)}
        self._reg_c["batches"].inc()
        self._reg_batch_tokens.labels(
            engine_id=self.engine_id, bucket=bucket_len).inc(valid_tokens)
        self._reg_batch_slots.labels(
            engine_id=self.engine_id, bucket=bucket_len).inc(rows * row_len)
        self.batch_requests.observe(n_requests)

    def packing_efficiency(self):
        """Aggregate fraction of dispatched slots holding real tokens
        (dummy pad rows from row-count quantization included — the
        honest number the chip actually paid for)."""
        with self._lock:
            if not self._slots:
                return None
            return self._valid_tokens / float(self._slots)

    def snapshot(self):
        with self._lock:
            counters = dict(self._c)
            slots, valid = self._slots, self._valid_tokens
            last = dict(self._last_batch) if self._last_batch else None
        out = {"engine_id": self.engine_id,
               "counters": counters,
               "queue_depth": (self._queue_depth_fn()
                               if self._queue_depth_fn else None),
               "latency": {"queue": self.queue_ms.snapshot(),
                           "pack": self.pack_ms.snapshot(),
                           "compute": self.compute_ms.snapshot(),
                           "compile": self.compile_ms.snapshot(),
                           "total": self.total_ms.snapshot()},
               "dispatched_slots": slots,
               "valid_tokens": valid,
               "packing_efficiency":
                   round(valid / float(slots), 4) if slots else None,
               "last_batch": last}
        return out
