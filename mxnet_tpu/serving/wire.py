"""Binary dispatch wire: the persistent router↔engine transport.

Two things live here:

1. **The typed, NON-EXECUTABLE frame codec.** Born in ``kvstore.py``
   for the dist_async parameter-server channel (its first cut spoke
   pickled frames — i.e. any peer that could reach the port could run
   arbitrary code), it is now the repo's ONE wire encoding, shared by
   the dist_async RPCs and the serving dispatch protocol below:
   a tagged tree of plain data (None/bool/int/float/str/bytes/dict/
   tuple) plus ndarrays as a struct header (dtype, shape) + raw buffer
   bytes. Decoding can only ever build data, never import or call
   anything; every malformed-frame failure surfaces as ``ValueError``
   so servers have ONE refusal path, and frame/ndarray sizes are
   capped (no 'length bomb' allocations).

2. **The dispatch protocol** replacing the router's JSON-over-HTTP
   long-poll (`_RemoteSeat` used to pay a fresh TCP connection, a
   dedicated waiter thread, and a full ``tokens.tolist()`` → JSON →
   ``np.asarray`` round-trip per in-flight request):

   - :class:`WireListener` — the engine side, started from
     ``ServingEngine.expose()`` alongside the HTTP server
     (``MXNET_TPU_WIRE*`` knobs). One reader thread per accepted
     connection feeds the existing submit path; results ride back
     through a per-connection writer thread, so a slow peer can never
     stall the engine worker.
   - :class:`WireClient` — the router side: a small pool of
     PERSISTENT multiplexed connections (``MXNET_TPU_WIRE_CONNS``).
     A single reader thread per connection demuxes RESULT/ERROR
     frames by correlation id — zero threads spawned per request.

   Frames are codec-encoded tuples, length-prefixed on the stream::

       ("HELLO",  {client/engine identity, "version": 1})
       ("SUBMIT", corr_id, {"tokens": int32 ndarray, "token_types",
                            "deadline_ms", "trace_id", "span_id",
                            tenancy: "model_id", "tenant",
                            "tenant_class",
                            decode: "max_new_tokens", "eos_id",
                            "stream", "temperature", "top_k", "top_p",
                            "seed"})
       ("RESULT", corr_id, {"result": ndarray, "cost", "breakdown",
                            "engine_ms", "trace_id"})
       ("ERROR",  corr_id, {"error_type", "error"})
       ("PING", n) / ("PONG", n)

   The decode sampling fields ride the SUBMIT frame itself (validated
   at engine admission — an out-of-range value comes back as an ERROR
   frame with ``error_type: InvalidSamplingError``, never a NaN from
   the compiled step), so a router re-dispatching the request after a
   seat failure replays the SAME seed: the replacement seat resamples
   the identical token sequence and the part-index dedupe works on
   sampled streams exactly as on greedy ones.

   Raw typed ndarray payloads — no ``tolist()`` — are the point: the
   dominant per-request overhead at high QPS was serialization.
   ``trace_id``/``span_id`` ride the SUBMIT frame so engine-side span
   trees parent under the router's ``router/request`` root exactly as
   they did over HTTP (the same crossing the dist_async wire uses).

Hostile-frame discipline (mirrors the dist_async server): an
undecodable or oversized frame refuses THE CONNECTION (the stream has
lost framing), an unknown frame type or garbage correlation id errors
THE FRAME (framing is intact), and neither ever kills the process.
"""
from __future__ import annotations

import itertools
import socket
import struct
import threading
import time
from collections import deque

import numpy as np

from .. import envvars
from ..base import MXNetError
from ..retrying import Reconnector
from ..telemetry import events as _events
from . import metrics as _metrics

__all__ = ["wire_encode", "wire_decode", "send_frame", "recv_frame",
           "WireError", "FrameTooLargeError", "WireListener",
           "WireClient", "PROTOCOL_VERSION"]

PROTOCOL_VERSION = 1

FRAME_HELLO = "HELLO"
FRAME_SUBMIT = "SUBMIT"
FRAME_RESULT = "RESULT"
FRAME_ERROR = "ERROR"
FRAME_PING = "PING"
FRAME_PONG = "PONG"


class WireError(MXNetError):
    """A dispatch-wire transport failure (connection down, handshake
    mismatch, in-flight request orphaned). The router maps it onto
    :class:`~.router.RemoteEngineError` — i.e. failover-eligible."""


class FrameTooLargeError(MXNetError, ValueError):
    """A length prefix (or ndarray header) promises more bytes than the
    channel's cap — refused BEFORE allocation. Subclasses ValueError
    (the codec's single refusal type) and MXNetError (what kvstore's
    dist_async channel historically raised here)."""


# -- typed frame codec ------------------------------------------------------
#   N none | T true | F false | i int64 | f float64
#   s utf-8 str | b bytes        (u32 length prefix)
#   a ndarray: u8 dtype-str-len + dtype.str + u8 ndim + u64*ndim + raw
#   l tuple:  u32 count + items
#   d dict:   u32 count + key/value item pairs
_WIRE_MAX_DEPTH = 16
MAX_FRAME_DEFAULT = 1 << 33        # 8 GiB: dist_async pushes big grads


def _enc(obj, out, depth=0):
    if depth > _WIRE_MAX_DEPTH:
        raise ValueError("wire object nests too deep")
    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, (int, np.integer)):
        out.append(b"i" + struct.pack("<q", int(obj)))
    elif isinstance(obj, (float, np.floating)):
        out.append(b"f" + struct.pack("<d", float(obj)))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(b"s" + struct.pack("<I", len(raw)) + raw)
    elif isinstance(obj, (bytes, bytearray)):
        out.append(b"b" + struct.pack("<I", len(obj)) + bytes(obj))
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise ValueError("object arrays are not wire-encodable")
        dt = obj.dtype.str.encode("ascii")
        out.append(b"a" + struct.pack("<B", len(dt)) + dt
                   + struct.pack("<B", obj.ndim)
                   + struct.pack(f"<{obj.ndim}Q", *obj.shape))
        out.append(np.ascontiguousarray(obj).tobytes())
    elif isinstance(obj, (list, tuple)):
        out.append(b"l" + struct.pack("<I", len(obj)))
        for item in obj:
            _enc(item, out, depth + 1)
    elif isinstance(obj, dict):
        out.append(b"d" + struct.pack("<I", len(obj)))
        for k, v in obj.items():
            _enc(k, out, depth + 1)
            _enc(v, out, depth + 1)
    else:
        raise ValueError(
            f"type {type(obj).__name__} is not wire-encodable (only "
            "plain data rides the wire)")
    return out


def _dec(buf, pos, depth=0):
    if depth > _WIRE_MAX_DEPTH:
        raise ValueError("wire object nests too deep")
    tag = buf[pos:pos + 1]
    pos += 1
    if tag == b"N":
        return None, pos
    if tag == b"T":
        return True, pos
    if tag == b"F":
        return False, pos
    if tag == b"i":
        return struct.unpack_from("<q", buf, pos)[0], pos + 8
    if tag == b"f":
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if tag in (b"s", b"b"):
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        raw = bytes(buf[pos:pos + n])
        if len(raw) != n:
            raise ValueError("truncated wire frame")
        return (raw.decode("utf-8") if tag == b"s" else raw), pos + n
    if tag == b"a":
        (dl,) = struct.unpack_from("<B", buf, pos)
        pos += 1
        dt = np.dtype(bytes(buf[pos:pos + dl]).decode("ascii"))
        pos += dl
        if dt.hasobject:
            raise ValueError("object arrays are not wire-decodable")
        (ndim,) = struct.unpack_from("<B", buf, pos)
        pos += 1
        shape = struct.unpack_from(f"<{ndim}Q", buf, pos)
        pos += 8 * ndim
        count = int(np.prod(shape, dtype=np.int64)) if ndim else 1
        nbytes = count * dt.itemsize
        if nbytes > MAX_FRAME_DEFAULT or pos + nbytes > len(buf):
            raise ValueError("truncated/oversized ndarray frame")
        arr = np.frombuffer(buf, dt, count=count, offset=pos).reshape(shape)
        return arr.copy(), pos + nbytes   # copy: own the memory
    if tag == b"l":
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _dec(buf, pos, depth + 1)
            items.append(item)
        return tuple(items), pos
    if tag == b"d":
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        out = {}
        for _ in range(n):
            k, pos = _dec(buf, pos, depth + 1)
            v, pos = _dec(buf, pos, depth + 1)
            out[k] = v
        return out, pos
    raise ValueError(f"unknown wire tag {bytes(tag)!r} — refusing frame")


def wire_encode(obj) -> bytes:
    return b"".join(_enc(obj, []))


def wire_decode(data) -> object:
    try:
        obj, pos = _dec(memoryview(data), 0)
    except ValueError:
        raise
    except (struct.error, TypeError, UnicodeDecodeError, IndexError,
            OverflowError, MemoryError) as e:
        # every malformed-frame failure surfaces as ValueError so the
        # server's bad-frame handling has ONE refusal path
        raise ValueError(f"malformed wire frame: {e!r}") from e
    if pos != len(data):
        raise ValueError("trailing bytes in wire frame")
    return obj


def send_frame(sock, obj, max_frame=None):
    """Encode + length-prefix + send; returns the frame's byte size so
    callers can account wire traffic without re-encoding."""
    data = wire_encode(obj)
    cap = max_frame if max_frame is not None else MAX_FRAME_DEFAULT
    if len(data) > cap:
        raise FrameTooLargeError(
            f"wire frame of {len(data)} bytes exceeds the cap ({cap})")
    sock.sendall(struct.pack("<Q", len(data)) + data)
    return len(data)


def recv_frame(sock, max_frame=None):
    """(decoded object, frame bytes) — None on a cleanly closed peer.
    A length prefix past ``max_frame`` raises BEFORE allocating."""
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    cap = max_frame if max_frame is not None else MAX_FRAME_DEFAULT
    if n > cap:
        raise FrameTooLargeError(
            f"wire frame of {n} bytes exceeds the cap ({cap})")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return wire_decode(bytes(buf)), n


def _max_frame_bytes():
    return int(envvars.get("MXNET_TPU_WIRE_MAX_FRAME_MB")) << 20


# -- shared plumbing --------------------------------------------------------
class _FrameWriter:
    """The WRITE half of one wire socket: frames queue here and a
    dedicated writer thread encodes + sends them. Completion callbacks
    (which run on the engine's worker thread) and the router's
    dispatcher therefore NEVER block on a slow peer's socket — the one
    thread that may is this writer, whose stall harms only its own
    connection."""

    def __init__(self, sock, name, max_frame, on_sent=None):
        self._sock = sock
        self._max_frame = max_frame
        self._on_sent = on_sent       # (frame_tag, nbytes) accounting
        self._dq = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def send(self, frame):
        """Queue one frame; False when the writer is already closed
        (the caller's peer is gone — nothing to do with the frame)."""
        with self._cv:
            if self._closed:
                return False
            self._dq.append(frame)
            self._cv.notify()
        return True

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def _run(self):
        while True:
            with self._cv:
                while not self._dq and not self._closed:
                    self._cv.wait(0.5)
                if not self._dq:
                    return              # closed and drained
                frame = self._dq.popleft()
            try:
                n = send_frame(self._sock, frame,
                               max_frame=self._max_frame)
            except (OSError, ValueError) as e:
                # peer gone or frame unencodable: this connection is
                # done; the owner notices via its reader (EOF) — leave
                # a trace rather than dying silently (thread-hygiene)
                _events.emit("wire_writer_error", error=repr(e))
                self.close()
                return
            if self._on_sent is not None:
                tag = frame[0] if isinstance(frame, tuple) and frame \
                    else "?"
                self._on_sent(tag, n)


def _hard_close(sock):
    """shutdown(SHUT_RDWR) + close. A bare ``close()`` on a socket
    whose OWN reader thread is blocked in ``recv`` does not release
    the kernel socket on Linux (the in-flight syscall holds the file
    reference) — no FIN is sent, the PEER never sees EOF, and a
    killed connection looks alive from the other side forever.
    ``shutdown`` tears the TCP stream down immediately and wakes the
    blocked reader regardless."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _safe_callback(cb, *args):
    """Invoke a completion callback; a broken observer must not kill
    the wire thread that delivered its result (same contract as
    InferenceFuture callbacks)."""
    try:
        cb(*args)
    except Exception as e:
        _events.emit("wire_callback_error", error=repr(e))


# -- engine side ------------------------------------------------------------
class WireListener:
    """Binary dispatch listener for one :class:`~.engine.ServingEngine`
    — or, with ``handler=``, for any frame-served peer surface (the
    router's active/active HA journal channel reuses exactly this
    listener with a synchronous handler instead of an engine).

    Started by ``ServingEngine.expose()`` next to the HTTP exposition
    server (``MXNET_TPU_WIRE=0`` opts out); the port is advertised in
    ``/healthz`` as ``wire_port`` so a fronting router can upgrade its
    dispatch transport without configuration. The submit path is the
    ENGINE's — admission errors ride back as ERROR frames carrying the
    serving taxonomy's class name, results as RESULT frames with the
    raw typed ndarray (no ``tolist()``) plus the request's amortized
    cost bill and the engine-observed wall (``engine_ms``, the router's
    dispatch-overhead baseline).

    ``handler(payload_dict) -> body_dict`` (when given) serves each
    SUBMIT frame synchronously on the connection's reader thread —
    right for instant bookkeeping ops (the HA journal), wrong for
    model forwards (which keep the engine's async future path). A
    raising handler errors THE FRAME with the exception's class name,
    never the connection.
    """

    def __init__(self, engine=None, host="127.0.0.1", port=None,
                 max_frame=None, owner_id=None, handler=None,
                 side="engine"):
        if engine is None and handler is None:
            raise ValueError("WireListener needs an engine or a handler")
        self._engine = engine
        self._handler = handler
        self._owner_id = str(owner_id) if owner_id is not None \
            else (engine.engine_id if engine is not None else "?")
        self._side = str(side)
        self._max_frame = (int(max_frame) if max_frame is not None
                           else _max_frame_bytes())
        eid = self._owner_id
        frames = _metrics.wire_frames_counter()
        self._f_in = {}
        self._f_out = {}
        self._frames = frames
        byt = _metrics.wire_bytes_counter()
        self._b_in = byt.labels(side=self._side, transport="wire",
                                direction="in")
        self._b_out = byt.labels(side=self._side, transport="wire",
                                 direction="out")
        self._conns_g = _metrics.wire_connections_gauge() \
            .labels(side=self._side)
        self._refusals = _metrics.wire_refusals_counter() \
            .labels(side=self._side)
        self._closed = False
        self._lock = threading.Lock()
        self._open = set()            # live connection sockets
        # chaos receive hook (serving.chaos): None when chaos is off —
        # nothing is patched, the per-frame cost is one attribute read
        self.chaos_rx = None
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        want = int(port if port is not None
                   else envvars.get("MXNET_TPU_WIRE_PORT"))
        try:
            srv.bind((host, want))
        except OSError:
            if not want:
                raise
            # the configured port is taken (two engines in one
            # process): an ephemeral port beats no wire at all — the
            # router discovers whatever /healthz advertises
            _events.emit("wire_port_fallback", engine_id=eid, port=want)
            srv.bind((host, 0))
        srv.listen(16)
        self._srv = srv
        threading.Thread(target=self._accept_loop,
                         name=f"mxnet_tpu_wire_accept_{eid}",
                         daemon=True).start()
        _events.emit("wire_listen", engine_id=eid, host=host,
                     port=self.port)

    @property
    def port(self):
        return self._srv.getsockname()[1]

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = list(self._open)
        try:
            self._srv.close()
        except OSError:
            pass
        for conn in conns:
            _hard_close(conn)         # unblocks readers, FINs peers

    def kill_connections(self):
        """Abruptly close every ACCEPTED connection (the listener keeps
        listening — peers reconnect). The chaos harness's
        ``kill_wire`` fault; also a handy drill primitive. Returns the
        number of connections killed."""
        with self._lock:
            conns = list(self._open)
        for conn in conns:
            # shutdown, not just close: this conn's own reader thread
            # is blocked in recv, and without SHUT_RDWR no FIN ever
            # reaches the peer — the "killed" connection would look
            # alive from the router side indefinitely
            _hard_close(conn)
        return len(conns)

    def _count_in(self, tag, n):
        child = self._f_in.get(tag)
        if child is None:
            child = self._f_in[tag] = self._frames.labels(
                side="engine", direction="in", frame=str(tag))
        child.inc()
        self._b_in.inc(n)

    def _count_out(self, tag, n):
        child = self._f_out.get(tag)
        if child is None:
            child = self._f_out[tag] = self._frames.labels(
                side="engine", direction="out", frame=str(tag))
        child.inc()
        self._b_out.inc(n)

    def _accept_loop(self):
        while True:
            try:
                conn, peer = self._srv.accept()
            except OSError:
                return
            with self._lock:
                if self._closed:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._open.add(conn)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._serve, args=(conn, peer),
                name=f"mxnet_tpu_wire_serve_fd{conn.fileno()}",
                daemon=True).start()

    def _serve(self, conn, peer):
        eid = self._owner_id
        self._conns_g.inc()
        writer = _FrameWriter(
            conn, f"mxnet_tpu_wire_write_fd{conn.fileno()}",
            self._max_frame, on_sent=self._count_out)
        try:
            while True:
                got = recv_frame(conn, max_frame=self._max_frame)
                if got is None:
                    return
                frame, nbytes = got
                if not isinstance(frame, tuple) or not frame:
                    raise ValueError(
                        "dispatch frame must be a tagged tuple, got "
                        f"{type(frame).__name__}")
                tag = frame[0]
                self._count_in(tag if isinstance(tag, str) else "?",
                               nbytes)
                rx = self.chaos_rx
                if rx is not None and not rx(tag):
                    continue        # chaos dropped the inbound frame
                if tag == FRAME_PING:
                    writer.send((FRAME_PONG,) + tuple(frame[1:2]))
                elif tag == FRAME_HELLO:
                    writer.send((FRAME_HELLO,
                                 {"engine_id": eid,
                                  "version": PROTOCOL_VERSION,
                                  "max_frame": self._max_frame}))
                elif tag == FRAME_SUBMIT:
                    self._handle_submit(frame, writer)
                else:
                    # unknown frame TYPE with intact framing: error the
                    # frame, keep the connection (a newer peer may mix
                    # frame kinds this engine predates)
                    corr = frame[1] if len(frame) > 1 \
                        and isinstance(frame[1], int) else None
                    self._error_frame(writer, corr,
                                      f"unknown frame type {tag!r}")
        except (ValueError, MXNetError) as e:
            # undecodable / oversized / mistyped frame: the STREAM has
            # lost framing — drop this client, keep serving the rest
            self._refusals.inc()
            _events.emit("wire_frame_refused", engine_id=eid,
                         peer=str(peer), error=str(e))
            return
        except (ConnectionError, EOFError, OSError):
            return
        finally:
            writer.close()
            with self._lock:
                self._open.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
            self._conns_g.dec()

    def _error_frame(self, writer, corr, message, error_type="WireError"):
        self._refusals.inc()
        writer.send((FRAME_ERROR, corr,
                     {"error_type": error_type, "error": message,
                      "engine_id": self._owner_id}))

    def _handle_submit(self, frame, writer):
        corr = frame[1] if len(frame) > 1 else None
        payload = frame[2] if len(frame) > 2 else None
        if not isinstance(corr, int):
            # garbage correlation id: the peer could never match a
            # reply to its request — error the frame, never the process
            self._error_frame(writer, None,
                              f"bad correlation id {corr!r}")
            return
        if not isinstance(payload, dict):
            self._error_frame(writer, corr,
                              "SUBMIT payload must be a dict")
            return
        if self._handler is not None:
            # synchronous peer-surface op (e.g. the router HA journal):
            # instant bookkeeping, answered inline on the reader
            # thread; a raising handler errors THE FRAME with the
            # exception's class name, keeping the connection
            try:
                body = self._handler(payload)
            except Exception as e:
                writer.send((FRAME_ERROR, corr,
                             {"error_type": type(e).__name__,
                              "error": str(e),
                              "engine_id": self._owner_id}))
                return
            writer.send((FRAME_RESULT, corr,
                         dict(body or {}, engine_id=self._owner_id)))
            return
        t0 = time.perf_counter()
        submit_payload = getattr(self._engine, "submit_payload", None)
        try:
            if submit_payload is not None:
                # decode engines take the whole payload (generation
                # params + the stream flag ride the same dict)
                fut, streamed = submit_payload(payload)
            else:
                fut = self._engine.submit(
                    payload.get("tokens"), payload.get("token_types"),
                    deadline_ms=payload.get("deadline_ms"),
                    trace_id=payload.get("trace_id"),
                    parent_span_id=payload.get("span_id"),
                    model_id=payload.get("model_id"),
                    tenant=payload.get("tenant"),
                    tenant_class=payload.get("tenant_class"))
                streamed = False
        except Exception as e:
            # admission failure (queue full, too long, stopped,
            # malformed tokens): the class name rides back so the
            # router re-raises the same serving taxonomy
            writer.send((FRAME_ERROR, corr,
                         {"error_type": type(e).__name__,
                          "error": str(e),
                          "engine_id": self._engine.engine_id}))
            return

        if streamed:
            # one partial RESULT frame per generated token, demuxed by
            # the SAME correlation id ("seq" orders, "final": False
            # marks the partial; the frame stays MINIMAL — the
            # correlation id already names the request, trace id and
            # cost ride the final body). A peer that never asked for
            # streaming gets exactly one RESULT with no "final" key —
            # the pre-streaming protocol, so old peers keep working.
            def _part(_f, part):
                writer.send((FRAME_RESULT, corr,
                             {"seq": int(part.get("index", 0)),
                              "token": part.get("token"),
                              "final": False}))

            fut.add_part_callback(_part)

        def _done(f):
            engine_ms = round((time.perf_counter() - t0) * 1e3, 3)
            exc = f.exception(timeout=0)
            if exc is not None:
                writer.send((FRAME_ERROR, corr,
                             {"error_type": type(exc).__name__,
                              "error": str(exc),
                              "engine_ms": engine_ms,
                              "engine_id": self._engine.engine_id}))
                return
            body = {"result": np.asarray(f.result(timeout=0)),
                    "cost": f.cost,
                    # the engine-measured critical path rides the
                    # final RESULT frame verbatim, like cost: router
                    # and loadgen must see the same numbers
                    "breakdown": getattr(f, "breakdown", None),
                    "trace_id": f.trace_id,
                    "engine_ms": engine_ms,
                    "engine_id": self._engine.engine_id}
            if streamed:
                # the final frame carries the AUTHORITATIVE full
                # sequence: a client that lost partials (killed
                # connection) misses nothing, one that has them can
                # verify seq count
                body["final"] = True
                body["seq"] = len(f.parts())
            writer.send((FRAME_RESULT, corr, body))

        fut.add_done_callback(_done)


# -- router side ------------------------------------------------------------
class _WireConn:
    """One persistent connection: socket + writer thread + reader
    thread + the in-flight correlation table the reader demuxes."""

    __slots__ = ("sock", "writer", "reader", "pending", "plock",
                 "alive", "pongs")

    def __init__(self, sock):
        self.sock = sock
        self.writer = None
        self.reader = None
        # corr_id -> (on_done, deadline, on_part, timeout_s); a
        # streamed partial refreshes the deadline (token progress IS
        # liveness)
        self.pending = {}
        self.plock = threading.Lock()
        self.alive = True
        self.pongs = {}               # ping nonce -> Event


class WireClient:
    """Router-side half: a pool of persistent multiplexed connections
    to one engine's dispatch listener.

    ``dispatch`` registers the request under a fresh correlation id
    and queues a SUBMIT frame — no blocking I/O, no thread creation on
    the dispatch path. Each connection's single reader thread demuxes
    RESULT/ERROR frames back to the registered callbacks; a connection
    dying fails ITS in-flight requests with :class:`WireError` (the
    router's failover requeues them — nothing is lost). ``ensure()``
    performs the blocking connect/handshake work and belongs on the
    router's poll thread, never the dispatcher.
    """

    def __init__(self, host, port, client_id, expect_engine_id=None,
                 conns=None, timeout_s=None, max_frame=None):
        self._host = str(host)
        self._port = int(port)
        self._client_id = str(client_id)
        self._expect = (str(expect_engine_id)
                        if expect_engine_id is not None else None)
        self._n = max(1, int(conns if conns is not None
                             else envvars.get("MXNET_TPU_WIRE_CONNS")))
        self._timeout = float(timeout_s if timeout_s is not None
                              else envvars.get("MXNET_TPU_WIRE_TIMEOUT_S"))
        self._max_frame = (int(max_frame) if max_frame is not None
                           else _max_frame_bytes())
        self._slots = [None] * self._n
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._corr = itertools.count(1)
        self._ping_seq = itertools.count(1)
        self._closed = False
        self._connect_failed = False  # edge-triggered event spam guard
        # repo-wide reconnect policy (mxnet_tpu.retrying): consecutive
        # failed connects back off 0.2 s doubling to a 5 s cap, so a
        # dead peer costs one connect per backoff window, not one per
        # poll tick; any success resets the ladder
        self._recon = Reconnector()
        frames = _metrics.wire_frames_counter()
        self._frames = frames
        self._f_in = {}
        self._f_out = {}
        byt = _metrics.wire_bytes_counter()
        self._b_in = byt.labels(side="router", transport="wire",
                                direction="in")
        self._b_out = byt.labels(side="router", transport="wire",
                                 direction="out")
        self._conns_g = _metrics.wire_connections_gauge() \
            .labels(side="router")

    @property
    def port(self):
        return self._port

    def _count_in(self, tag, n):
        child = self._f_in.get(tag)
        if child is None:
            child = self._f_in[tag] = self._frames.labels(
                side="router", direction="in", frame=str(tag))
        child.inc()
        self._b_in.inc(n)

    def _count_out(self, tag, n):
        child = self._f_out.get(tag)
        if child is None:
            child = self._f_out[tag] = self._frames.labels(
                side="router", direction="out", frame=str(tag))
        child.inc()
        self._b_out.inc(n)

    # -- connection management (poll thread) -------------------------------
    def ensure(self):
        """(Re)connect any dead slot. Blocking (connect + handshake) —
        call from the health-poll thread. Returns the live count.
        Consecutive failed connects are backoff-gated by the shared
        :class:`~mxnet_tpu.retrying.Reconnector` policy — a dead peer
        is not re-dialed on every poll tick."""
        live = 0
        for i in range(self._n):
            with self._lock:
                if self._closed:
                    return live
                conn = self._slots[i]
            if conn is not None and conn.alive:
                live += 1
                continue
            if not self._recon.ready():
                return live     # backing off a recent failed connect
            try:
                fresh = self._connect()
            except (OSError, MXNetError, ValueError) as e:
                self._recon.failed()
                if not self._connect_failed:
                    self._connect_failed = True
                    _events.emit("wire_connect_error",
                                 host=self._host, port=self._port,
                                 engine_id=self._expect, error=repr(e))
                return live
            self._connect_failed = False
            self._recon.succeeded()
            stale = None
            with self._lock:
                if self._closed:
                    stale = fresh
                else:
                    stale, self._slots[i] = self._slots[i], fresh
                    live += 1
            if stale is fresh:
                self._teardown(fresh)
                return live
            if stale is not None:
                self._teardown(stale)
        return live

    def _connect(self):
        sock = socket.create_connection((self._host, self._port),
                                        timeout=self._timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # handshake runs SYNCHRONOUSLY (still on the poll thread)
            # before the reader spins up: a port serving some other
            # protocol — or a replacement engine under a recycled
            # port — must be rejected before any SUBMIT rides it
            send_frame(sock, (FRAME_HELLO,
                              {"client_id": self._client_id,
                               "version": PROTOCOL_VERSION}),
                       max_frame=self._max_frame)
            sock.settimeout(self._timeout)
            got = recv_frame(sock, max_frame=self._max_frame)
            if got is None:
                raise WireError("peer closed during wire handshake")
            frame, _n = got
            if not (isinstance(frame, tuple) and frame
                    and frame[0] == FRAME_HELLO):
                raise WireError(f"bad wire handshake reply: {frame!r}")
            info = frame[1] if len(frame) > 1 \
                and isinstance(frame[1], dict) else {}
            eid = info.get("engine_id")
            if (self._expect is not None and eid is not None
                    and str(eid) != self._expect):
                raise WireError(
                    f"wire port answered as engine {eid!r}, expected "
                    f"{self._expect!r} (stale port?)")
            sock.settimeout(None)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        conn = _WireConn(sock)
        fd = sock.fileno()
        conn.writer = _FrameWriter(
            sock, f"mxnet_tpu_wire_write_fd{fd}", self._max_frame,
            on_sent=self._count_out)
        conn.reader = threading.Thread(
            target=self._read_loop, args=(conn,),
            name=f"mxnet_tpu_wire_read_fd{fd}", daemon=True)
        conn.reader.start()
        self._conns_g.inc()
        return conn

    def has_live(self):
        with self._lock:
            return any(c is not None and c.alive for c in self._slots)

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = [c for c in self._slots if c is not None]
            self._slots = [None] * self._n
        for conn in conns:
            self._teardown(conn)

    def kill_connections(self):
        """Abruptly tear down every live connection WITHOUT closing
        the client (``ensure`` reconnects on the next tick) — the
        chaos harness's router-side ``kill_wire`` fault. In-flight
        requests fail with :class:`WireError`, i.e. the router's
        failover requeues them. Returns the number killed."""
        with self._lock:
            conns = [c for c in self._slots if c is not None]
            self._slots = [None] * self._n
        for conn in conns:
            self._teardown(conn)
        return len(conns)

    def _teardown(self, conn, error=None):
        with conn.plock:
            was_alive, conn.alive = conn.alive, False
            orphans = list(conn.pending.items())
            conn.pending.clear()
            pongs = list(conn.pongs.values())
            conn.pongs.clear()
        if not was_alive and not orphans:
            return
        conn.writer.close()
        _hard_close(conn.sock)        # FIN + wake the blocked reader
        if was_alive:
            self._conns_g.dec()
        for evt in pongs:
            evt.set()
        exc = WireError(
            f"wire connection to {self._host}:{self._port} lost"
            + (f": {error!r}" if error is not None else "")
            + (f" ({len(orphans)} in flight)" if orphans else ""))
        for _corr, entry in orphans:
            _safe_callback(entry[0], exc, None)

    # -- dispatch (router dispatcher thread) --------------------------------
    def dispatch(self, payload, on_done, timeout_s, on_part=None):
        """Queue one SUBMIT on a live connection. ``on_done(exc, body)``
        fires exactly once: with the RESULT/ERROR frame body (exc None)
        on the connection's reader thread, or with a :class:`WireError`
        when the connection dies or the reply outlives ``timeout_s``.
        ``on_part(body)`` (optional) fires once per streamed partial
        RESULT frame (``final: False``) BEFORE the final delivery;
        each partial refreshes the reply deadline — a long generation
        making token progress is alive, only a silent one times out.
        Raises :class:`WireError` when no live connection exists — the
        caller falls back (HTTP) or fails over."""
        deadline = time.monotonic() + float(timeout_s) + self._timeout
        for _ in range(self._n):
            i = next(self._rr) % self._n
            with self._lock:
                conn = self._slots[i]
            if conn is None or not conn.alive:
                continue
            corr = next(self._corr)
            with conn.plock:
                if not conn.alive:
                    continue
                conn.pending[corr] = (on_done, deadline, on_part,
                                      float(timeout_s))
            if not conn.writer.send((FRAME_SUBMIT, corr, payload)):
                with conn.plock:
                    delivered = conn.pending.pop(corr, None) is None
                if delivered:
                    # a teardown raced in between registering the
                    # pending entry and the failed send: it already
                    # fired on_done(WireError) — trying another
                    # connection here would deliver twice
                    return corr
                continue
            return corr
        raise WireError(
            f"no live wire connection to {self._host}:{self._port}")

    def ping(self, timeout_s=None):
        """Round-trip a PING on one live connection; True on PONG."""
        nonce = next(self._ping_seq)
        evt = threading.Event()
        for _ in range(self._n):
            i = next(self._rr) % self._n
            with self._lock:
                conn = self._slots[i]
            if conn is None or not conn.alive:
                continue
            with conn.plock:
                if not conn.alive:
                    continue
                conn.pongs[nonce] = evt
            if not conn.writer.send((FRAME_PING, nonce)):
                with conn.plock:
                    conn.pongs.pop(nonce, None)
                continue
            ok = evt.wait(timeout_s if timeout_s is not None
                          else self._timeout)
            with conn.plock:
                conn.pongs.pop(nonce, None)
            return ok and conn.alive
        return False

    def sweep(self):
        """Fail in-flight requests whose reply outlived the dispatch
        timeout (poll-thread housekeeping — the reader can't notice a
        reply that never comes). They fail with WireError, i.e. the
        router's failover requeues them."""
        now = time.monotonic()
        for conn in list(self._slots):
            if conn is None:
                continue
            expired = []
            with conn.plock:
                for corr, entry in list(conn.pending.items()):
                    if now > entry[1]:
                        expired.append((corr, entry[0]))
                        del conn.pending[corr]
            for corr, on_done in expired:
                _safe_callback(on_done, WireError(
                    f"wire dispatch {corr} to {self._host}:"
                    f"{self._port} timed out"), None)

    # -- reader (one thread per connection) ---------------------------------
    def _read_loop(self, conn):
        err = None
        try:
            while True:
                got = recv_frame(conn.sock, max_frame=self._max_frame)
                if got is None:
                    break
                frame, nbytes = got
                tag = frame[0] if isinstance(frame, tuple) and frame \
                    else None
                self._count_in(tag if isinstance(tag, str) else "?",
                               nbytes)
                if tag in (FRAME_RESULT, FRAME_ERROR) \
                        and len(frame) >= 3:
                    corr = frame[1]
                    body = frame[2] if isinstance(frame[2], dict) \
                        else {"error_type": "WireError",
                              "error": "malformed reply body"}
                    if tag == FRAME_RESULT \
                            and body.get("final") is False:
                        # streamed partial: deliver to the part hook,
                        # KEEP the pending entry, refresh its deadline
                        # (token progress is liveness). A peer
                        # streaming at a non-streaming entry (no
                        # on_part) is ignored — the final RESULT still
                        # resolves it.
                        on_part = None
                        with conn.plock:
                            entry = (conn.pending.get(corr)
                                     if isinstance(corr, int) else None)
                            if entry is not None \
                                    and entry[2] is not None:
                                on_done, _dl, on_part, t_s = entry
                                conn.pending[corr] = (
                                    on_done,
                                    time.monotonic() + t_s
                                    + self._timeout, on_part, t_s)
                        if on_part is not None:
                            _safe_callback(on_part, body)
                        continue
                    with conn.plock:
                        entry = (conn.pending.pop(corr, None)
                                 if isinstance(corr, int) else None)
                    if entry is None:
                        # garbage/duplicate correlation id from the
                        # peer: nothing to deliver to — count it, keep
                        # the connection (framing is intact)
                        _events.emit("wire_unknown_correlation",
                                     host=self._host, port=self._port,
                                     corr=repr(corr))
                        continue
                    _safe_callback(entry[0], None, body)
                elif tag == FRAME_PONG and len(frame) >= 2:
                    with conn.plock:
                        evt = conn.pongs.pop(frame[1], None)
                    if evt is not None:
                        evt.set()
                else:
                    _events.emit("wire_unknown_frame",
                                 host=self._host, port=self._port,
                                 frame=repr(tag))
        except (ConnectionError, EOFError, OSError, ValueError,
                MXNetError) as e:
            err = e
        finally:
            self._teardown(conn, error=err)
