"""Evaluation metrics (python/mxnet/metric.py analog).

Same API surface: ``EvalMetric`` base with update/get/reset,
``CompositeEvalMetric``, a ``create`` registry, and the classes the
reference ships: Accuracy, TopKAccuracy, F1, MCC, MAE, MSE, RMSE,
CrossEntropy, NegativeLogLikelihood, PearsonCorrelation, Perplexity,
Loss, Torch/Caffe placeholders omitted. Metric math runs on host numpy
— metrics are sync points by nature (reference does the same via
asnumpy in each update)."""
from __future__ import annotations

import math

import numpy

from .base import _Registry
from .ndarray import NDArray

__all__ = [
    "EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy", "F1",
    "MCC", "MAE", "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
    "PearsonCorrelation", "Perplexity", "Loss", "CustomMetric", "np",
    "create", "register",
]

_REG = _Registry("metric")


def register(klass=None, name=None):
    if klass is None:
        return lambda k: register(k, name)
    _REG.register((name or klass.__name__).lower())(klass)
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _REG.get(metric)(*args, **kwargs)


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return numpy.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if isinstance(labels, NDArray):
        labels = [labels]
    if isinstance(preds, NDArray):
        preds = [preds]
    if len(labels) != len(preds):
        raise ValueError(f"Shape of labels {len(labels)} does not match shape of predictions {len(preds)}")
    return labels, preds


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get()))}"

    def get_config(self):
        config = dict(self._kwargs)
        config.update({"metric": type(self).__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.global_sum_metric / self.global_num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def _inc(self, metric, inst):
        self.sum_metric += metric
        self.num_inst += inst
        self.global_sum_metric += metric
        self.global_num_inst += inst


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def reset_local(self):
        for metric in getattr(self, "metrics", []):
            metric.reset_local()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, numpy.generic)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


@register(name="acc")
@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label)
            if pred.ndim > label.ndim:
                pred = numpy.argmax(pred, axis=self.axis)
            pred = pred.astype(numpy.int32).flat
            label = label.astype(numpy.int32).flat
            correct = int((numpy.asarray(pred) == numpy.asarray(label)).sum())
            self._inc(correct, len(numpy.asarray(label)))


@register(name="top_k_accuracy")
@register(name="top_k_acc")
@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += f"_{self.top_k}"

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label).astype(numpy.int32)
            assert pred.ndim == 2
            arg = numpy.argsort(pred, axis=1)[:, ::-1][:, :self.top_k]
            correct = int((arg == label.reshape(-1, 1)).any(axis=1).sum())
            self._inc(correct, len(label))


class _BinaryClassificationHelper:
    def __init__(self):
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.tn = self.fn = 0

    def update(self, label, pred):
        pred_label = numpy.argmax(pred, axis=1) if pred.ndim > 1 else (pred > 0.5)
        label = label.astype(numpy.int32).reshape(-1)
        pred_label = numpy.asarray(pred_label).astype(numpy.int32).reshape(-1)
        self.tp += int(((pred_label == 1) & (label == 1)).sum())
        self.fp += int(((pred_label == 1) & (label == 0)).sum())
        self.tn += int(((pred_label == 0) & (label == 0)).sum())
        self.fn += int(((pred_label == 0) & (label == 1)).sum())

    @property
    def precision(self):
        return self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0

    @property
    def recall(self):
        return self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0

    @property
    def fscore(self):
        p, r = self.precision, self.recall
        return 2 * p * r / (p + r) if p + r else 0.0

    @property
    def matthewscc(self):
        terms = [(self.tp + self.fp), (self.tp + self.fn),
                 (self.tn + self.fp), (self.tn + self.fn)]
        denom = 1.0
        for t in terms:
            denom *= t if t else 1.0
        return ((self.tp * self.tn) - (self.fp * self.fn)) / math.sqrt(denom)

    @property
    def total_examples(self):
        return self.tp + self.fp + self.tn + self.fn


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average
        self.metrics = _BinaryClassificationHelper()

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.metrics.update(_as_np(label), _as_np(pred))
            if self.average == "macro":
                self._inc(self.metrics.fscore, 1)
                self.metrics.reset_stats()

    def get(self):
        if self.average == "macro":
            return super().get()
        if self.metrics.total_examples == 0:
            return (self.name, float("nan"))
        return (self.name, self.metrics.fscore)

    def reset(self):
        super().reset()
        if hasattr(self, "metrics"):
            self.metrics.reset_stats()


@register
class MCC(F1):
    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names, average)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.metrics.update(_as_np(label), _as_np(pred))
            if self.average == "macro":
                self._inc(self.metrics.matthewscc, 1)
                self.metrics.reset_stats()

    def get(self):
        if self.average == "macro":
            return EvalMetric.get(self)
        if self.metrics.total_examples == 0:
            return (self.name, float("nan"))
        return (self.name, self.metrics.matthewscc)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._inc(float(numpy.abs(label - pred).mean()), 1)


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self._inc(float(((label - pred) ** 2).mean()), 1)


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        name, value = super().get()
        return (name, math.sqrt(value) if not math.isnan(value) else value)


@register(name="ce")
@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel().astype(numpy.int64)
            pred = _as_np(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), label]
            self._inc(float((-numpy.log(prob + self.eps)).sum()), label.shape[0])


@register(name="nll_loss")
@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register(name="pearsonr")
@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label).ravel(), _as_np(pred).ravel()
            self._inc(float(numpy.corrcoef(pred, label)[0, 1]), 1)


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_np(label).reshape(-1).astype(numpy.int64)
            pred = _as_np(pred).reshape(label.shape[0], -1)
            probs = pred[numpy.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = numpy.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= float(numpy.log(numpy.maximum(1e-10, probs)).sum())
            num += label.shape[0]
        self._inc(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            loss = float(_as_np(pred).sum())
            self._inc(loss, int(numpy.prod(_as_np(pred).shape)))


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = getattr(feval, "__name__", "custom")
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            label, pred = _as_np(label), _as_np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self._inc(sum_metric, num_inst)
            else:
                self._inc(reval, 1)


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy eval function into a CustomMetric (mx.metric.np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = getattr(numpy_feval, "__name__", "custom")
    return CustomMetric(feval, name, allow_extra_outputs)
