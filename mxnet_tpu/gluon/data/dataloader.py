"""DataLoader (python/mxnet/gluon/data/dataloader.py analog).

Worker model parity with the reference (multiprocessing workers +
shared-memory NDArray rebuild, CPUSharedStorageManager):

- ``num_workers>0, thread_pool=False`` (the reference default): a
  forked PROCESS pool decodes and batchifies to numpy outside the GIL
  (Python/PIL decode does not scale on threads — SURVEY §7 hard part
  #6); the parent converts to device arrays. Workers never touch JAX
  (fork + XLA runtime don't mix); ``default_mp_batchify_fn`` therefore
  stacks to numpy, the parent wraps.
- ``thread_pool=True``: thread workers — cheaper startup, right when
  __getitem__ is numpy-bound and GIL-releasing.
- :class:`DevicePrefetcher` overlaps host→device transfer with compute
  (the PrefetcherIter/pin-memory role; PJRT device_put is async).
"""
from __future__ import annotations

import concurrent.futures as _futures
import multiprocessing as _mp
import threading
from collections import deque

import numpy as np

from ...base import MXNetError
from ...ndarray import NDArray, array
from ...telemetry import events as _telemetry_events
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "DevicePrefetcher", "default_batchify_fn",
           "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        from ... import ndarray as nd
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return array(data)


def default_mp_batchify_fn(data):
    """Worker-side batchify: numpy ONLY. A forked worker must never
    touch JAX — the parent holds a multithreaded XLA client and any
    device call after fork can deadlock — so NDArray samples are
    rejected with a fix-it message instead of being converted."""
    if isinstance(data[0], NDArray):
        raise MXNetError(
            "Dataset.__getitem__ returned an NDArray but the DataLoader "
            "uses forked process workers, which must not touch device "
            "arrays. Return numpy from the dataset/transforms, or pass "
            "thread_pool=True (thread workers), or num_workers=0.")
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_mp_batchify_fn(i) for i in data]
    return np.asarray(data)


def _to_nd(batch):
    if isinstance(batch, np.ndarray):
        return array(batch)
    if isinstance(batch, (list, tuple)):
        return [_to_nd(b) for b in batch]
    return batch


# worker globals installed by the pool initializer (fork start method:
# the dataset is inherited copy-on-write — no per-task pickling)
_WORKER_DATASET = None
_WORKER_FN = None

# arrays above this size ride shared memory instead of the result pipe —
# the CPUSharedStorageManager role: pickling a 20MB batch through a pipe
# costs more than the decode itself
_SHM_MIN_BYTES = 1 << 20


def _worker_init(dataset, batchify_fn):
    global _WORKER_DATASET, _WORKER_FN
    _WORKER_DATASET = dataset
    _WORKER_FN = batchify_fn


def _ship(obj):
    """Replace large numpy arrays with shared-memory descriptors."""
    if isinstance(obj, np.ndarray) and obj.nbytes >= _SHM_MIN_BYTES:
        from multiprocessing import shared_memory
        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)[...] = obj
        name = shm.name
        shm.close()
        return ("__shm__", name, obj.shape, str(obj.dtype))
    if isinstance(obj, (list, tuple)):
        return [_ship(o) for o in obj]
    return obj


def _receive(obj):
    """Materialize shared-memory descriptors: one host memcpy out of
    the segment, unlink immediately, return numpy — the (async) device
    transfer happens downstream (_to_nd / DevicePrefetcher), so the
    result-drain loop never blocks on H2D."""
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__shm__":
        from multiprocessing import shared_memory
        _, name, shape, dtype = obj
        shm = shared_memory.SharedMemory(name=name)
        try:
            out = np.array(np.ndarray(shape, np.dtype(dtype), buffer=shm.buf),
                           copy=True)
        finally:
            shm.close()
            shm.unlink()
        return out
    if isinstance(obj, (list, tuple)):
        return [_receive(o) for o in obj]
    return obj


def _discard_shm(obj):
    """Unlink shared-memory descriptors without materializing them."""
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == "__shm__":
        from multiprocessing import shared_memory
        try:
            shm = shared_memory.SharedMemory(name=obj[1])
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
        return
    if isinstance(obj, (list, tuple)):
        for o in obj:
            _discard_shm(o)


def _worker_task(indices):
    return _ship(_WORKER_FN([_WORKER_DATASET[i] for i in indices]))


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._mp_pool = None  # persistent worker pool (created lazily);
        # assigned FIRST so __del__ is safe if validation below raises
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._num_workers = max(0, num_workers)
        self._thread_pool = thread_pool
        self._timeout = timeout
        self._prefetch = max(0, prefetch or 2 * self._num_workers)
        self._fork_safe = None  # probed lazily on first __iter__

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is "
                                 "specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch must "
                             "not be specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        if batchify_fn is None:
            batchify_fn = default_mp_batchify_fn \
                if (self._num_workers > 0 and not thread_pool) \
                else default_batchify_fn
        self._batchify_fn = batchify_fn

    def _get_mp_pool(self):
        """Fork the worker pool ONCE and keep it across epochs
        (reference keeps workers alive too; forking a parent that holds
        an accelerator client is expensive — seconds per worker)."""
        if self._mp_pool is None:
            ctx = _mp.get_context("fork")
            self._mp_pool = ctx.Pool(
                self._num_workers, initializer=_worker_init,
                initargs=(self._dataset, self._batchify_fn))
        return self._mp_pool

    def __del__(self):
        pool = getattr(self, "_mp_pool", None)
        if pool is not None:
            try:
                pool.terminate()
            except Exception:
                pass  # interpreter teardown: helpers may be gone already

    def __iter__(self):
        if self._num_workers == 0:
            def same_process_iter():
                for batch in self._batch_sampler:
                    yield self._batchify_fn([self._dataset[idx] for idx in batch])
            return same_process_iter()
        if not self._thread_pool:
            if self._fork_safe is None:
                # fork the pool BEFORE probing: the probe may materialize
                # lazy dataset state (open record files) in the parent,
                # and forked workers must inherit the clean instance —
                # a shared fd means interleaved seek/read corruption
                self._get_mp_pool()
                if not self._dataset_is_fork_safe():
                    # probe says thread fallback: don't keep idle forks
                    self._mp_pool.terminate()
                    self._mp_pool = None
            if self._fork_safe:
                return _MultiProcessIter(self)
        return _ThreadedIter(self)

    def _dataset_is_fork_safe(self):
        """Forked workers must not touch JAX: probe one sample and fall
        back to thread workers (with the eager batchify) when
        __getitem__ produces device arrays (e.g. the vision datasets'
        NDArray transforms). Call only AFTER the pool forked (see
        __iter__)."""
        if self._fork_safe is None:
            def has_nd(x):
                if isinstance(x, NDArray):
                    return True
                if isinstance(x, (list, tuple)):
                    return any(has_nd(i) for i in x)
                return False
            try:
                self._fork_safe = not has_nd(self._dataset[0])
            except Exception:
                self._fork_safe = False
            if not self._fork_safe and self._batchify_fn is default_mp_batchify_fn:
                self._batchify_fn = default_batchify_fn
        return self._fork_safe

    def __len__(self):
        return len(self._batch_sampler)


class _ThreadedIter:
    """Thread-pool prefetching iterator (PrefetcherIter analog)."""

    def __init__(self, loader: DataLoader):
        self._loader = loader
        self._pool = _futures.ThreadPoolExecutor(
            max_workers=loader._num_workers,
            thread_name_prefix="mxnet_tpu_dataloader_prefetch")
        self._batches = iter(loader._batch_sampler)
        self._pending = deque()
        for _ in range(loader._prefetch):
            self._submit_next()

    def _submit_next(self):
        try:
            batch = next(self._batches)
        except StopIteration:
            return
        fn = self._loader._batchify_fn
        ds = self._loader._dataset
        self._pending.append(
            self._pool.submit(lambda b: fn([ds[i] for i in b]), batch))

    def __iter__(self):
        return self

    def __next__(self):
        if not self._pending:
            self._shutdown()
            raise StopIteration
        fut = self._pending.popleft()
        self._submit_next()
        try:
            return fut.result()
        except Exception:
            self._shutdown()
            raise

    def _shutdown(self):
        self._pool.shutdown(wait=False, cancel_futures=True)

    def __del__(self):
        # abandoned mid-epoch (break/early stop): release worker threads
        self._shutdown()


class _MultiProcessIter:
    """Forked process-pool iterator (reference multiprocessing workers):
    decode/batchify run outside the GIL; batches come back as numpy and
    are wrapped to NDArrays in the parent."""

    def __init__(self, loader: DataLoader):
        self._loader = loader
        self._pool = loader._get_mp_pool()
        self._batches = iter(loader._batch_sampler)
        self._pending = deque()
        for _ in range(max(loader._prefetch, loader._num_workers)):
            self._submit_next()

    def _submit_next(self):
        try:
            batch = next(self._batches)
        except StopIteration:
            return
        self._pending.append(self._pool.apply_async(_worker_task, (list(batch),)))

    def __iter__(self):
        return self

    def __next__(self):
        if not self._pending:
            self._shutdown()
            raise StopIteration
        res = self._pending.popleft()
        self._submit_next()
        try:
            out = res.get(timeout=self._loader._timeout)
        except Exception:
            self._shutdown()
            raise
        return _to_nd(_receive(out))

    def _shutdown(self):
        # the pool belongs to the DataLoader (persistent across epochs),
        # but in-flight results hold shared-memory segments that only
        # _receive unlinks — drain and discard them or /dev/shm leaks a
        # batch per abandoned epoch
        while self._pending:
            res = self._pending.popleft()
            try:
                _discard_shm(res.get(timeout=self._loader._timeout))
            except Exception as e:
                # keep draining (every leaked result pins /dev/shm),
                # but a discard that itself fails is worth a trace
                _telemetry_events.emit("dataloader_discard_error",
                                       error=repr(e))

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


class DevicePrefetcher:
    """Wraps a batch iterable; keeps ``depth`` batches already
    device_put to ``ctx`` so the accelerator never waits on H2D
    (reference PrefetcherIter + pin_memory role).

    ``threaded=True`` (default) runs source-pull + device_put on a
    dedicated thread, so decode waits and H2D RPCs overlap the
    consumer's step dispatches (double-buffering; the consumer only
    blocks when the queue is empty). ``threaded=False`` keeps the
    simple synchronous fill."""

    def __init__(self, it, ctx=None, depth=2, threaded=True):
        from ...context import current_context
        self._src = iter(it)
        self._ctx = ctx or current_context()
        self._depth = max(1, depth)
        self._queue = deque()
        self._threaded = bool(threaded)
        self._worker = None
        if self._threaded:
            import queue as _q
            import threading as _t
            self._q = _q.Queue(maxsize=self._depth)
            self._done = object()
            self._stop = False
            self._exhausted = False

            def put(item):
                # bounded put that gives up when the consumer closes —
                # a plain q.put would pin this thread (and depth device
                # batches) forever if iteration stops early
                while not self._stop:
                    try:
                        self._q.put(item, timeout=0.1)
                        return True
                    except _q.Full:
                        continue
                return False

            def pump():
                try:
                    for batch in self._src:
                        if not put(self._to_device(batch)):
                            return
                except BaseException as e:  # surfaced on the consumer
                    put(e)
                # ALWAYS terminate the stream: without the sentinel a
                # consumer that survives the raised error deadlocks on
                # the next get()
                put(self._done)

            self._worker = _t.Thread(target=pump, daemon=True)
            self._worker.start()

    def close(self):
        """Stop the pump thread and release queued device batches
        (safe to call repeatedly; no-op for the synchronous mode)."""
        if self._worker is None:
            return
        self._stop = True
        try:
            while True:
                self._q.get_nowait()
        except Exception:
            pass
        self._worker.join(timeout=2.0)
        self._worker = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _to_device(self, batch):
        if isinstance(batch, NDArray):
            return batch.as_in_context(self._ctx)
        if isinstance(batch, np.ndarray):
            return array(batch, ctx=self._ctx)
        if isinstance(batch, (list, tuple)):
            return [self._to_device(b) for b in batch]
        return batch

    def _fill(self):
        while len(self._queue) < self._depth:
            try:
                self._queue.append(self._to_device(next(self._src)))
            except StopIteration:
                break

    def __iter__(self):
        return self

    def __next__(self):
        if self._threaded:
            if self._exhausted or (self._worker is None
                                   and self._q.empty()):
                raise StopIteration  # repeatable: pump is gone
            item = self._q.get()
            if item is self._done:
                self._exhausted = True
                raise StopIteration
            if isinstance(item, BaseException):
                raise item
            return item
        self._fill()
        if not self._queue:
            raise StopIteration
        out = self._queue.popleft()
        self._fill()
        return out
