"""DataLoader (python/mxnet/gluon/data/dataloader.py analog).

The reference uses multiprocessing workers + shared-memory NDArray
rebuild (CPUSharedStorageManager). TPU-native design: worker THREADS
(batchify is numpy-bound and releases the GIL; jax device_put is the
only hot conversion) + a prefetch queue that overlaps host batch
assembly with device steps. `num_workers>0` enables the threaded
prefetcher; the API (batchify_fn, samplers, pin_memory) is preserved —
pin_memory is a no-op because PJRT host buffers are already DMA-able.
"""
from __future__ import annotations

import concurrent.futures as _futures
import threading
from collections import deque

import numpy as np

from ...base import MXNetError
from ...ndarray import NDArray, array
from .sampler import SequentialSampler, RandomSampler, BatchSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        from ... import ndarray as nd
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return array(data)


default_mp_batchify_fn = default_batchify_fn


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch or 2 * self._num_workers)

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is "
                                 "specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch must "
                             "not be specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn

    def __iter__(self):
        if self._num_workers == 0:
            def same_process_iter():
                for batch in self._batch_sampler:
                    yield self._batchify_fn([self._dataset[idx] for idx in batch])
            return same_process_iter()
        return _ThreadedIter(self)

    def __len__(self):
        return len(self._batch_sampler)


class _ThreadedIter:
    """Thread-pool prefetching iterator (PrefetcherIter analog)."""

    def __init__(self, loader: DataLoader):
        self._loader = loader
        self._pool = _futures.ThreadPoolExecutor(max_workers=loader._num_workers)
        self._batches = iter(loader._batch_sampler)
        self._pending = deque()
        for _ in range(loader._prefetch):
            self._submit_next()

    def _submit_next(self):
        try:
            batch = next(self._batches)
        except StopIteration:
            return
        fn = self._loader._batchify_fn
        ds = self._loader._dataset
        self._pending.append(
            self._pool.submit(lambda b: fn([ds[i] for i in b]), batch))

    def __iter__(self):
        return self

    def __next__(self):
        if not self._pending:
            self._shutdown()
            raise StopIteration
        fut = self._pending.popleft()
        self._submit_next()
        try:
            return fut.result()
        except Exception:
            self._shutdown()
            raise

    def _shutdown(self):
        self._pool.shutdown(wait=False, cancel_futures=True)

    def __del__(self):
        # abandoned mid-epoch (break/early stop): release worker threads
        self._shutdown()
