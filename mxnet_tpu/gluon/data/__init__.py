from .dataset import Dataset, SimpleDataset, ArrayDataset, RecordFileDataset
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler
from .dataloader import DataLoader, DevicePrefetcher
# sequence packing batchify (variable-length corpora -> fixed packed
# rows for the segment-aware flash-attention path; worker-safe numpy)
from ...io.packing import PackedBatchify
from . import vision
