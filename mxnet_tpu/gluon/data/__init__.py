from .dataset import Dataset, SimpleDataset, ArrayDataset, RecordFileDataset
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler
from .dataloader import DataLoader, DevicePrefetcher
from . import vision
