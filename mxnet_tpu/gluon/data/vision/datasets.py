"""Vision datasets (python/mxnet/gluon/data/vision/datasets.py analog).

No network egress in the TPU sandbox: datasets load from local files
(`root` must contain the standard archives/idx files); when files are
absent and `synthetic_fallback` is on (default for tests), a
deterministic synthetic replacement with the right shapes is generated
— keeps the training-loop surface exercisable offline.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ....base import MXNetError
from ...data.dataset import Dataset, ArrayDataset
from ....ndarray import array

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform, synthetic_fallback=True):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        self._synthetic = synthetic_fallback
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from local idx files (train-images-idx3-ubyte(.gz) etc.)."""

    _shape = (28, 28, 1)
    _nclass = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None, synthetic_fallback=True):
        self._train = train
        super().__init__(root, transform, synthetic_fallback)

    def _file_names(self):
        if self._train:
            return "train-images-idx3-ubyte", "train-labels-idx1-ubyte"
        return "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"

    def _get_data(self):
        img_name, lbl_name = self._file_names()
        img_path = self._find(img_name)
        lbl_path = self._find(lbl_name)
        if img_path is None or lbl_path is None:
            if not self._synthetic:
                raise MXNetError(
                    f"MNIST files not found under {self._root} and network "
                    "download is unavailable")
            n = 6000 if self._train else 1000
            rng = np.random.default_rng(42 + int(self._train))
            self._label = rng.integers(0, self._nclass, n).astype(np.int32)
            base = rng.normal(0, 0.05, (self._nclass,) + self._shape)
            noise = rng.normal(0, 0.1, (n,) + self._shape)
            data = np.clip(base[self._label] + noise + 0.1307, 0, 1)
            self._data = array((data * 255).astype(np.uint8))
            return
        self._label = _read_idx(lbl_path).astype(np.int32)
        self._data = array(_read_idx(img_path).reshape(-1, 28, 28, 1))

    def _find(self, name):
        for cand in (name, name + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.isfile(p):
                return p
        return None


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        _, _, dims = struct.unpack(">HBB", f.read(4))
        shape = tuple(struct.unpack(">I", f.read(4))[0] for _ in range(dims))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None, synthetic_fallback=True):
        super().__init__(root, train, transform, synthetic_fallback)


class CIFAR10(_DownloadedDataset):
    _shape = (32, 32, 3)
    _nclass = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None, synthetic_fallback=True):
        self._train = train
        super().__init__(root, transform, synthetic_fallback)

    def _get_data(self):
        # expects cifar-10-binary.tar.gz extracted or the .bin files present
        files = [f"data_batch_{i}.bin" for i in range(1, 6)] if self._train \
            else ["test_batch.bin"]
        paths = []
        for f in files:
            for cand in (os.path.join(self._root, f),
                         os.path.join(self._root, "cifar-10-batches-bin", f)):
                if os.path.isfile(cand):
                    paths.append(cand)
                    break
        if len(paths) != len(files):
            if not self._synthetic:
                raise MXNetError(f"CIFAR10 files not found under {self._root}")
            n = 5000 if self._train else 1000
            rng = np.random.default_rng(1234 + int(self._train))
            self._label = rng.integers(0, self._nclass, n).astype(np.int32)
            base = rng.normal(0, 0.08, (self._nclass,) + self._shape)
            data = np.clip(base[self._label] +
                           rng.normal(0, 0.15, (n,) + self._shape) + 0.45, 0, 1)
            self._data = array((data * 255).astype(np.uint8))
            return
        data_list, label_list = [], []
        for p in paths:
            raw = np.frombuffer(open(p, "rb").read(), dtype=np.uint8)
            raw = raw.reshape(-1, 3073)
            label_list.append(raw[:, 0].astype(np.int32))
            data_list.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                             .transpose(0, 2, 3, 1))
        self._label = np.concatenate(label_list)
        self._data = array(np.concatenate(data_list))


class CIFAR100(CIFAR10):
    _nclass = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None,
                 synthetic_fallback=True):
        self._fine_label = fine_label
        super().__init__(root, train, transform, synthetic_fallback)

    def _get_data(self):
        fname = "train.bin" if self._train else "test.bin"
        path = None
        for cand in (os.path.join(self._root, fname),
                     os.path.join(self._root, "cifar-100-binary", fname)):
            if os.path.isfile(cand):
                path = cand
                break
        if path is None:
            if not self._synthetic:
                raise MXNetError(f"CIFAR100 files not found under {self._root}")
            CIFAR10._get_data(self)
            return
        raw = np.frombuffer(open(path, "rb").read(), dtype=np.uint8)
        raw = raw.reshape(-1, 3074)
        self._label = raw[:, 1 if self._fine_label else 0].astype(np.int32)
        self._data = array(raw[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))


class ImageRecordDataset(Dataset):
    """Dataset over a RecordIO file of packed images."""

    def __init__(self, filename, flag=1, transform=None):
        from ...data.dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio, image
        record = self._record[idx]
        header, img_bytes = recordio.unpack(record)
        img = image.imdecode(img_bytes, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._record)


class ImageFolderDataset(Dataset):
    """A dataset of images arranged root/class/image.ext."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".npy"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from .... import image
        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = array(np.load(path))
        else:
            with open(path, "rb") as f:
                img = image.imdecode(f.read(), self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
