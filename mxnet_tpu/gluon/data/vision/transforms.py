"""Vision transforms (python/mxnet/gluon/data/vision/transforms.py analog)."""
from __future__ import annotations

import numpy as np

from ....base import MXNetError
from ....ndarray import NDArray, array
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "RandomResizedCrop",
           "CenterCrop", "Resize", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation"]


class Compose(Sequential):
    """Sequentially composes multiple transforms."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def __init__(self):
        super().__init__()

    def hybrid_forward(self, F, x):
        out = F.Cast(x, dtype="float32") / 255.0
        if out.ndim == 3:
            return out.transpose((2, 0, 1))
        return out.transpose((0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def hybrid_forward(self, F, x):
        mean = array(self._mean, ctx=x.ctx) if isinstance(x, NDArray) else self._mean
        std = array(self._std, ctx=x.ctx) if isinstance(x, NDArray) else self._std
        return (x - mean) / std


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._keep = keep_ratio

    def forward(self, x):
        from .... import image
        img = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        return array(image._resize_np(img, self._size[0], self._size[1]))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)

    def forward(self, x):
        from .... import image
        out, _ = image.center_crop(x, self._size)
        return out


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        from .... import image
        img = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            log_ratio = (np.log(self._ratio[0]), np.log(self._ratio[1]))
            aspect = np.exp(np.random.uniform(*log_ratio))
            new_w = int(round(np.sqrt(target_area * aspect)))
            new_h = int(round(np.sqrt(target_area / aspect)))
            if new_w <= w and new_h <= h:
                x0 = np.random.randint(0, w - new_w + 1)
                y0 = np.random.randint(0, h - new_h + 1)
                crop = img[y0:y0 + new_h, x0:x0 + new_w]
                return array(image._resize_np(crop, self._size[0], self._size[1]))
        return array(image._resize_np(img, self._size[0], self._size[1]))


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.random() < 0.5:
            img = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
            return array(np.ascontiguousarray(img[:, ::-1]))
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.random() < 0.5:
            img = x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)
            return array(np.ascontiguousarray(img[::-1]))
        return x


class _RandomColorJitterBase(Block):
    def __init__(self, brightness):
        super().__init__()
        self._jitter = brightness

    def _alpha(self):
        return 1.0 + np.random.uniform(-self._jitter, self._jitter)


class RandomBrightness(_RandomColorJitterBase):
    def forward(self, x):
        img = x.asnumpy().astype(np.float32) if isinstance(x, NDArray) \
            else np.asarray(x, np.float32)
        return array(np.clip(img * self._alpha(), 0, 255))


class RandomContrast(_RandomColorJitterBase):
    def forward(self, x):
        img = x.asnumpy().astype(np.float32) if isinstance(x, NDArray) \
            else np.asarray(x, np.float32)
        mean = img.mean()
        return array(np.clip((img - mean) * self._alpha() + mean, 0, 255))


class RandomSaturation(_RandomColorJitterBase):
    def forward(self, x):
        img = x.asnumpy().astype(np.float32) if isinstance(x, NDArray) \
            else np.asarray(x, np.float32)
        gray = img.mean(axis=-1, keepdims=True)
        a = self._alpha()
        return array(np.clip(img * a + gray * (1 - a), 0, 255))
