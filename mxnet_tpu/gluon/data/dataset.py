"""Datasets (python/mxnet/gluon/data/dataset.py analog)."""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def shard(self, num_shards, index):
        assert 0 <= index < num_shards
        length = len(self)
        shard_len = length // num_shards
        rest = length % num_shards
        start = shard_len * index + min(index, rest)
        end = start + shard_len + (index < rest)
        return _LazySlice(self, start, end)

    def take(self, count):
        return _LazySlice(self, 0, min(count, len(self)))

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazySlice(Dataset):
    def __init__(self, dataset, start, end):
        self._dataset = dataset
        self._start, self._end = start, end

    def __len__(self):
        return self._end - self._start

    def __getitem__(self, idx):
        return self._dataset[self._start + idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class ArrayDataset(Dataset):
    """Zip of arrays/datasets (reference ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, \
                f"All arrays must have the same length; array[0] has length " \
                f"{self._length} while array[{i}] has {len(data)}."
            if isinstance(data, (list, tuple)) or hasattr(data, "shape"):
                self._data.append(data)
            else:
                self._data.append(list(data))

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(data[idx] for data in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over an IndexedRecordIO file (reference RecordFileDataset)."""

    def __init__(self, filename):
        from ... import recordio
        self.idx_file = filename[:-4] + ".idx" if filename.endswith(".rec") \
            else filename + ".idx"
        self.filename = filename
        self._record = recordio.MXIndexedRecordIO(self.idx_file, self.filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)
