"""Gluon Block / HybridBlock / SymbolBlock
(python/mxnet/gluon/block.py analog).

``Block`` is the eager container (children registry, name scopes,
collect_params, save/load_parameters, hooks). ``HybridBlock`` adds
``hybridize()`` — the CachedOp analog (reference
src/imperative/cached_op.cc): the first hybridized call *traces*
``hybrid_forward`` into one jit-compiled XLA computation whose
arguments are (rng-key, inputs…, parameters…); subsequent calls with
the same input signature replay the compiled computation. The whole
compiled graph enters the autograd tape as ONE node via jax.vjp —
exactly CachedOp's role of "one engine op for the whole subgraph", with
XLA doing what nnvm PlanMemory/bulking did (`static_alloc`/
`static_shape` become XLA buffer planning, for free).

BatchNorm-style running statistics inside a trace are handled
functionally: layers register deferred aux updates which the tracer
returns as extra outputs and the caller writes back after execution
(the reference mutates aux NDArrays from inside the op; immutability
forces — and rewards — the functional form).
"""
from __future__ import annotations

import copy
import re
import threading
from collections import OrderedDict

import jax
import numpy as np

from ..base import MXNetError
from ..context import Context, current_context, cpu
from ..name import NameManager, Prefix
from ..ndarray import NDArray
from ..ndarray.ndarray import _wrap
from ..ndarray.register import Op, invoke
from .. import autograd as _autograd
from .. import random as _random
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name scope for parameter/prefix management."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = NameManager.current().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        self._name_scope = Prefix(self._block.prefix)
        self._name_scope.__enter__()
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return False
        self._name_scope.__exit__(ptype, value, trace)
        self._name_scope = None
        _BlockScope._current.value = self._old_scope
        return False


class Block:
    """Base class for all neural network layers and models."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params,
                                                        self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(f"  ({key}): {_indent(str(block), 2)}"
                           for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and not isinstance(value, type(existing)):
                raise TypeError(f"Changing attribute type for {name} from "
                                f"{type(existing)} to {type(value)} is not allowed.")
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def __getattr__(self, name):
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks)
        self._forward_hooks[handle.id] = hook
        return handle

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        from .. import ndarray as nd
        arg_dict = {key: val._reduce() for key, val in params.items()}
        nd.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        from .. import ndarray as nd
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not isinstance(loaded, dict):
            raise MXNetError(f"{filename} has no parameter names")
        if not loaded and not params:
            return
        # legacy full-name format fallback
        if not any("." in k for k in loaded.keys()) and \
                any(k.startswith(self.prefix) for k in loaded.keys()):
            del loaded
            self.collect_params().load(filename, ctx, allow_missing,
                                       ignore_extra, self.prefix)
            return
        if not allow_missing:
            for name in params.keys():
                assert name in loaded, \
                    f"Parameter '{name}' is missing in file '{filename}'"
        for name in loaded:
            if not ignore_extra and name not in params:
                raise MXNetError(
                    f"Parameter '{name}' loaded from file '{filename}' is not "
                    "present in this Block")
            if name in params:
                param = params[name]
                arr = loaded[name]
                if param._data is None and param._deferred_init:
                    param.shape = arr.shape
                    param._finish_deferred_init()
                elif param._data is None:
                    param._shape = arr.shape
                    param.initialize(ctx=ctx or [current_context()])
                if cast_dtype:
                    arr = arr.astype(param.dtype)
                param.set_data(arr)

    # legacy names
    save_params = save_parameters

    def load_params(self, filename, ctx=None, allow_missing=False, ignore_extra=False):
        self.load_parameters(filename, ctx, allow_missing, ignore_extra)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        raise NotImplementedError

    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError


class _HookHandle:
    _id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        _HookHandle._id += 1
        self.id = _HookHandle._id

    def detach(self):
        self._hooks.pop(self.id, None)


def _indent(s, num):
    lines = s.split("\n")
    return ("\n" + " " * num).join(lines)


# ----------------------------------------------------------------------
# trace guard: inside a CachedOp trace (or its shape dry-run) all blocks
# run pure-eager so a parent's compiled graph inlines its children
# (reference CachedOp also flattens the whole subgraph into one graph —
# nested CachedOps would mean nested jit with per-child rng draws)
# ----------------------------------------------------------------------
_TRACE_GUARD = threading.local()


def _in_cached_call() -> bool:
    return getattr(_TRACE_GUARD, "depth", 0) > 0


class _trace_guard:
    def __enter__(self):
        _TRACE_GUARD.depth = getattr(_TRACE_GUARD, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _TRACE_GUARD.depth -= 1
        return False


# outermost-wins guard for trace-time remat: hybridize(remat=True)
# propagates to children, but nesting jax.checkpoint inside an already
# checkpointed region just re-wraps recompute in recompute — the
# outermost flagged block claims the wrap and descendants run plain
_REMAT_GUARD = threading.local()


# ----------------------------------------------------------------------
# deferred aux updates (BatchNorm running stats inside a trace)
# ----------------------------------------------------------------------
_AUX_COLLECT = threading.local()


def _collecting_aux():
    return getattr(_AUX_COLLECT, "sink", None)


def defer_aux_update(param: Parameter, new_value):
    """Called by layers with running state. Inside a hybridize trace the
    new (traced) value is collected as an extra output; eagerly it is
    written immediately."""
    sink = _collecting_aux()
    if sink is not None:
        sink.append((param, new_value))
    else:
        with _autograd.pause():
            arr = param.data()
            arr._set_data(new_value._data if isinstance(new_value, NDArray)
                          else new_value)


class HybridBlock(Block):
    """Block that can be traced into one compiled XLA computation."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = {}
        self._cached_graph = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  remat=None, remat_policy=None, **kwargs):
        """Activate compiled execution. static_alloc/static_shape are
        accepted for API parity — XLA always plans memory statically.

        ``remat=True`` (TPU-first extension, no reference analog) wraps
        the compiled subgraph in ``jax.checkpoint``: the backward pass
        recomputes this block's activations instead of storing them —
        the HBM-for-FLOPs trade for long sequences / deep nets.
        Hybridize the root for whole-net remat, or mark children with
        ``child.hybridize(active=False, remat=True)`` for selective
        per-block checkpointing — a marked child is wrapped when any
        ancestor traces it (CachedOp or functionalize;
        :meth:`_remat_trace`). ``remat``/``remat_policy`` default to
        None = KEEP the block's existing setting, so a later parent
        ``net.hybridize()`` does not erase per-child marks; pass
        ``remat=False`` to clear explicitly. ``remat_policy`` selects
        what the forward saves (a ``jax.checkpoint_policies`` name, or
        "names:conv_out" to save conv outputs and recompute only the
        elementwise chain)."""
        prev = self._flags
        if remat is None:
            remat = prev.get("remat", False)
        if remat_policy is None:
            remat_policy = prev.get("remat_policy")
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape,
                           remat=remat, remat_policy=remat_policy, **kwargs)
        self._cached_graph = {}
        super().hybridize(active, **kwargs)

    def infer_shape(self, *args):
        """Finalize deferred parameter shapes from the input shapes.

        Parametrized layers override this (the reference runs symbolic
        shape inference over the traced graph; here each layer's shape
        rule is local and explicit — Dense/Conv/BatchNorm/... set their
        weight shapes from the first input)."""
        raise MXNetError(
            f"{type(self).__name__} has deferred-initialized parameters but "
            "does not implement infer_shape")

    def cast(self, dtype):
        super().cast(dtype)
        self._cached_graph = {}

    def __call__(self, *args):
        return super().__call__(*args)

    def forward(self, x, *args):
        """Route to hybrid_forward, eagerly or through the cached op."""
        if isinstance(x, NDArray):
            if self._active and not _in_cached_call():
                return self._call_cached_op(x, *args)
            if self._flags.get("remat") and _in_cached_call() \
                    and not getattr(_REMAT_GUARD, "active", False):
                return self._remat_trace(x, *args)
            return self._forward_eager(x, *args)
        # symbolic path (Symbol inputs → graph building)
        from .. import symbol as symmod
        from ..symbol import Symbol
        if isinstance(x, Symbol):
            params = {k: v.var() for k, v in self._reg_params.items()}
            with self.name_scope():
                return self.hybrid_forward(symmod, x, *args, **params)
        raise MXNetError(f"unsupported input type {type(x)}")

    def _forward_eager(self, x, *args):
        with x.ctx:
            try:
                params = {k: v.data(x.ctx) for k, v in self._reg_params.items()}
            except DeferredInitializationError:
                self._infer_param_shapes(x, *args)
                params = {k: v.data(x.ctx) for k, v in self._reg_params.items()}
            from .. import ndarray as ndmod
            # np-style hybrid blocks reach the numpy namespaces through
            # F.np / F.npx (the deep-numpy convention; attributes are
            # installed on the nd package by mxnet_tpu/__init__) while
            # classic F.<op> names stay exactly as before
            return self.hybrid_forward(ndmod, x, *args, **params)

    def _remat_trace(self, x, *args):
        """Inside a parent trace, run this block under ``jax.checkpoint``:
        the backward pass recomputes the block's activations instead of
        reading them back from HBM (selective activation checkpointing —
        the TPU-native lever for bandwidth-bound backward passes; the
        reference has a coarse graph-level analog in mirror mode,
        docs/faq/env_var.md MXNET_BACKWARD_DO_MIRROR).

        The wrapped function is pure: (rng-key, inputs, params) →
        (outputs, aux updates). Running-stat updates surface as extra
        checkpoint outputs and re-enter the outer trace's aux sink; a
        subkey of the active trace key is passed in explicitly so the
        backward recompute replays identical randomness (dropout masks
        match between forward and rebuild). ``remat_policy`` (a
        ``jax.checkpoint_policies`` name or callable) selects what the
        forward may save; default saves nothing but the inputs."""
        ctx = x.ctx
        try:
            params = list(self.collect_params().values())
            p_datas = [p.data(ctx)._data for p in params]
        except DeferredInitializationError:
            # shapes not concrete yet (dry-run trace) — plain eager pass;
            # the real trace after init takes the checkpointed path
            return self._forward_eager(x, *args)
        arg_template = [x] + list(args)
        in_datas = [a._data for a in arg_template if isinstance(a, NDArray)]
        box = {}
        block = self

        def pure(rng_key, in_datas, p_datas):
            it = iter(in_datas)
            call_args = [_wrap(next(it), ctx) if isinstance(a, NDArray) else a
                         for a in arg_template]
            saved = [(p, p._data) for p in params]
            outer_sink = getattr(_AUX_COLLECT, "sink", None)
            sink: list = []
            _AUX_COLLECT.sink = sink
            _random.push_trace_key(rng_key)
            prev_remat = getattr(_REMAT_GUARD, "active", False)
            _REMAT_GUARD.active = True
            try:
                for p, d in zip(params, p_datas):
                    p._data = {c: _wrap(d, c) for c in p._data}
                out = block._forward_eager(*call_args)
            finally:
                _REMAT_GUARD.active = prev_remat
                for p, d in saved:
                    p._data = d
                _AUX_COLLECT.sink = outer_sink
                _random.pop_trace_key()
            flat, structure = _flatten(out)
            box["structure"] = structure
            box["aux_params"] = [p for p, _ in sink]
            aux = tuple(n._data if isinstance(n, NDArray) else n
                        for _, n in sink)
            return tuple(f._data for f in flat), aux

        policy = self._flags.get("remat_policy")
        if isinstance(policy, str):
            if policy.startswith("names:"):
                # "names:conv_out[,other]" — save only values tagged with
                # jax.ad_checkpoint.checkpoint_name (Convolution tags its
                # output 'conv_out'): backward recomputes just the cheap
                # elementwise chain between saved anchors
                policy = jax.checkpoint_policies.save_only_these_names(
                    *policy[len("names:"):].split(","))
            else:
                policy = getattr(jax.checkpoint_policies, policy)
        ckpt = jax.checkpoint(pure, policy=policy)
        key = _random._next_key()
        out_datas, aux_datas = ckpt(key, in_datas, p_datas)
        for p, new in zip(box["aux_params"], aux_datas):
            defer_aux_update(p, _wrap(new, ctx))
        flat = [_wrap(d, ctx) for d in out_datas]
        return _unflatten(flat, box["structure"])

    def _infer_param_shapes(self, *args):
        """Finalize deferred init using the layer's shape rule, then retry.
        (Children finalize on their own first calls.)"""
        self.infer_shape(*args)
        for _, v in self._reg_params.items():
            v._finish_deferred_init()

    # -- the CachedOp analog ----------------------------------------------
    def _call_cached_op(self, *args):
        inputs = [a for a in args if isinstance(a, NDArray)]
        ctx = inputs[0].ctx if inputs else current_context()
        # make sure all params are concrete (deferred init finalized by an
        # eager dry-run if needed)
        try:
            params = list(self.collect_params().values())
            param_arrays = [p.data(ctx) for p in params]
        except DeferredInitializationError:
            with _autograd.pause(), _trace_guard():
                self.forward(*args)
            params = list(self.collect_params().values())
            param_arrays = [p.data(ctx) for p in params]

        training = _autograd.is_training()
        from ..ndarray.register import dispatch_cast_generation
        key = (tuple((tuple(a.shape), str(a.dtype)) for a in inputs), training,
               dispatch_cast_generation())  # AMP on/off → fresh trace
        entry = self._cached_graph.get(key)
        if entry is None:
            entry = self._build_cached_op(args, inputs, params, ctx, training)
            self._cached_graph[key] = entry
        op, structure, aux_params, n_flat_out = entry

        rng = _wrap(_random._next_key(), ctx)
        results = invoke(op, [rng] + inputs + param_arrays, {}, ctx=ctx)
        if not isinstance(results, list):
            results = [results]
        flat_out, aux_out = results[:n_flat_out], results[n_flat_out:]
        # write back running stats
        with _autograd.pause():
            for p, new in zip(aux_params, aux_out):
                p.data(ctx)._set_data(new._data)
        return _unflatten(flat_out, structure)

    def _build_cached_op(self, args, inputs, params, ctx, training):
        """Trace hybrid_forward into a jitted function (CachedOp ctor)."""
        # trace time is compile time: make sure the persistent
        # compilation cache is pointed at disk BEFORE the first jit,
        # so this executable outlives the process (warm restarts)
        from .. import compile_cache
        compile_cache.ensure()
        block = self
        n_in = len(inputs)
        arg_template = list(args)

        aux_params_order: list = []

        def traced(rng_key, *arrays):
            in_arrays = arrays[:n_in]
            p_arrays = arrays[n_in:]
            wrapped_inputs = [_wrap(a, ctx) for a in in_arrays]
            # rebuild the positional args with traced NDArrays
            it = iter(wrapped_inputs)
            call_args = [next(it) if isinstance(a, NDArray) else a
                         for a in arg_template]
            _random.push_trace_key(rng_key)
            sink: list = []
            _AUX_COLLECT.sink = sink
            saved_data = [(p, p._data) for p in params]
            prev_train = _autograd.set_training(training)
            prev_rec = _autograd.set_recording(False)
            prev_remat = getattr(_REMAT_GUARD, "active", False)
            if block._flags.get("remat"):
                # whole-block remat is applied at the jit level below —
                # keep forward() from re-wrapping this same block (and
                # any descendant) in a nested trace-time checkpoint
                _REMAT_GUARD.active = True
            try:
                with _trace_guard():
                    for p, arr in zip(params, p_arrays):
                        wrappers = {c: _wrap(arr, c) for c in p._data}
                        p._data = wrappers
                    out = block.forward(*call_args)
            finally:
                _REMAT_GUARD.active = prev_remat
                for p, d in saved_data:
                    p._data = d
                _autograd.set_recording(prev_rec)
                _autograd.set_training(prev_train)
                _AUX_COLLECT.sink = None
                _random.pop_trace_key()
            flat, structure = _flatten(out)
            aux_arrays = []
            aux_params_order.clear()
            for p, new in sink:
                aux_params_order.append(p)
                aux_arrays.append(new._data if isinstance(new, NDArray) else new)
            traced._structure = structure
            return tuple(x._data if isinstance(x, NDArray) else x
                         for x in flat) + tuple(aux_arrays)

        fn = jax.checkpoint(traced) if self._flags.get("remat") else traced
        jitted = jax.jit(fn)
        # learn the output structure abstractly — no device execution
        # (jax.eval_shape runs the python once with avals; the real
        # compile+run happens on the first invoke below)
        rng = _random._next_key()
        sample = jax.eval_shape(traced, rng, *[a._data for a in inputs],
                                *[p.data(ctx)._data for p in params])
        structure = traced._structure
        n_flat_out = len(sample) - len(aux_params_order)
        op = Op(f"CachedOp_{self.name}", jitted, differentiable=True)
        return op, structure, list(aux_params_order), n_flat_out

    def export(self, path, epoch=0, remove_amp_cast=True):
        """Export model-symbol.json + params (reference HybridBlock.export)."""
        from .. import symbol as symmod
        from .. import ndarray as nd
        data = symmod.var("data")
        with _autograd.pause():
            try:
                sym = self(data)
            except Exception as e:
                raise MXNetError(
                    "export requires the block to support symbolic forward; "
                    f"tracing failed: {e}") from e
        if isinstance(sym, (list, tuple)):
            sym = symmod.Group(list(sym))
        sym.save(f"{path}-symbol.json")
        arg_dict = {}
        for name, param in self.collect_params().items():
            arg_dict[f"arg:{name}"] = param._reduce()
        nd.save(f"{path}-{epoch:04d}.params", arg_dict)
        return f"{path}-symbol.json", f"{path}-{epoch:04d}.params"

    def forward_symbolic(self, x, *args):
        return self.forward(x, *args)


def _flatten(out):
    """Flatten nested (list/tuple of) NDArrays → flat list + structure."""
    if isinstance(out, NDArray):
        return [out], "single"
    if isinstance(out, (list, tuple)):
        flat = []
        struct = []
        for o in out:
            f, s = _flatten(o)
            flat.extend(f)
            struct.append((s, len(f)))
        return flat, struct
    raise MXNetError(f"unsupported output type {type(out)}")


def _unflatten(flat, structure):
    if structure == "single":
        return flat[0]
    out = []
    i = 0
    for s, n in structure:
        if s == "single":
            out.append(flat[i])
        else:
            out.append(_unflatten(flat[i:i + n], s))
        i += n
    return out


def functionalize(block: Block, training: bool = False, ctx=None):
    """Pure-functional view of a block: returns ``(fn, params)`` where
    ``fn(param_arrays: dict, rng_key, *input_arrays) -> jax array(s)`` is
    jit-traceable and ``params`` maps parameter name → jax array.

    This is the bridge from the MXNet-shaped object API to the
    jit/pjit/shard_map world (SURVEY §7: the sharded Trainer fast path,
    __graft_entry__, and the benchmarks use it). The block must already
    be initialized (shapes concrete). BatchNorm running-stat updates are
    dropped inside the functional view (they are aux side effects; use
    the CachedOp path when you need them written back).
    """
    params = list(block.collect_params().values())
    if ctx is None:
        ctx = current_context()

    def fn(param_arrays, rng_key, *in_arrays):
        saved = [(p, p._data) for p in params]
        _random.push_trace_key(rng_key)
        prev_train = _autograd.set_training(training)
        prev_rec = _autograd.set_recording(False)
        prev_sink = getattr(_AUX_COLLECT, "sink", None)
        _AUX_COLLECT.sink = []
        try:
            with _trace_guard():
                for p in params:
                    arr = param_arrays[p.name]
                    p._data = {c: _wrap(arr, c) for c in p._data}
                # None inputs pass through untouched: optional
                # positional slots (e.g. BERTModel's mask between
                # valid_length and segment_ids) stay skippable from the
                # functional caller
                out = block(*[_wrap(a, ctx) if a is not None else None
                              for a in in_arrays])
        finally:
            for p, d in saved:
                p._data = d
            _autograd.set_recording(prev_rec)
            _autograd.set_training(prev_train)
            _AUX_COLLECT.sink = prev_sink
            _random.pop_trace_key()
        flat, structure = _flatten(out)
        arrays = tuple(x._data for x in flat)
        return arrays[0] if structure == "single" else arrays

    init_params = {p.name: p.data(ctx)._data for p in params}
    return fn, init_params


class SymbolBlock(HybridBlock):
    """Wrap an exported Symbol graph as a Block (reference SymbolBlock).
    Loads model-symbol.json + .params (the deployment path)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=None)
        from ..symbol import Symbol, Group
        if isinstance(outputs, (list, tuple)):
            outputs = Group(list(outputs))
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        self._symbol = outputs
        self._input_names = [i.name for i in inputs]
        arg_names = outputs.list_arguments()
        for name in arg_names:
            if name not in self._input_names:
                self.params.get(name, allow_deferred_init=True)
        if params is not None:
            for name, arr in params.items():
                clean = name
                for pfx in ("arg:", "aux:"):
                    if clean.startswith(pfx):
                        clean = clean[len(pfx):]
                p = self.params.get(clean, allow_deferred_init=True)
                p._shape = arr.shape
                p.initialize(ctx=[arr.ctx])
                p.set_data(arr)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as symmod
        from .. import ndarray as nd
        sym = symmod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [symmod.var(n) for n in input_names]
        params = nd.load(param_file) if param_file else None
        ret = SymbolBlock(sym, inputs, params)
        if ctx is not None and params is not None:
            ret.collect_params().reset_ctx(ctx)
        return ret

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            bindings = dict(zip(self._input_names, [x] + list(args)))
            for name, p in self.params.items():
                bindings[name] = p.data(x.ctx)
            outs = self._symbol._eval(bindings)
            return outs[0] if len(outs) == 1 else outs
        raise MXNetError("SymbolBlock only supports NDArray inputs")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError
