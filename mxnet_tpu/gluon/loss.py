"""Loss layers (python/mxnet/gluon/loss.py analog): L1/L2,
SoftmaxCrossEntropy, SigmoidBCE, KLDiv, CTC, Huber, Hinge/SquaredHinge,
Logistic, Triplet, Cosine."""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .block import HybridBlock

__all__ = [
    "Loss", "L2Loss", "L1Loss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
    "SigmoidBinaryCrossEntropyLoss", "SigmoidBCELoss", "KLDivLoss", "CTCLoss",
    "HuberLoss", "HingeLoss", "SquaredHingeLoss", "LogisticLoss",
    "TripletLoss", "CosineEmbeddingLoss",
]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{self.__class__.__name__}(batch_axis={self._batch_axis}, w={self._weight})"

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        ax = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=ax)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ax = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=ax)


class SoftmaxCrossEntropyLoss(Loss):
    """softmax + CE fused (reference gluon SoftmaxCrossEntropyLoss;
    log_softmax+pick keeps it numerically stable and XLA fuses it)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ax = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=ax)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * \
                    (F.Activation(-F.abs(pred), act_type="softrelu") + F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label, pos_weight)
                         + F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ax = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=ax)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ax = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=ax)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ax = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=ax)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ax = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=ax)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ax = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=ax)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed", **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format
        if self._label_format not in ["signed", "binary"]:
            raise ValueError(f"label_format can only be signed or binary, "
                             f"recieved {label_format}")

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        ax = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=ax)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        ax = tuple(range(1, pred.ndim))
        loss = F.sum(F.square(pred - positive) - F.square(pred - negative),
                     axis=ax)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = input1.reshape((input1.shape[0], -1))
        input2 = input2.reshape((input2.shape[0], -1))
        cos = F.sum(input1 * input2, axis=1) / (
            F.norm(input1, axis=1) * F.norm(input2, axis=1) + 1e-12)
        label = label.reshape((-1,))
        loss = F.where(label == 1, 1.0 - cos, F.relu(cos - self._margin))
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification
    (reference src/operator/nn/ctc_loss.cc / warp-ctc). Log-domain
    forward algorithm via lax.scan over time — see ops/ctc.py."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)
        assert layout in ("NTC", "TNC")
        assert label_layout in ("NT", "TN")
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)  # → TNC
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)
        from ..ops.ctc import ctc_loss_nd
        loss = ctc_loss_nd(pred, label, pred_lengths, label_lengths)
        return _apply_weighting(F, loss, self._weight, sample_weight)
