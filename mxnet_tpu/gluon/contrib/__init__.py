from . import nn
from . import rnn
from . import estimator
