"""Contrib recurrent cells (gluon/contrib/rnn/rnn_cell.py analog)."""
from __future__ import annotations

from ...rnn.rnn_cell import ModifierCell, BidirectionalCell

__all__ = ["VariationalDropoutCell"]


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask across time steps (variational RNN dropout)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        assert not drop_states or not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _initialize_mask(self, F, p, like):
        return F.Dropout(F.ones_like(like), p=p)

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        if self.drop_states:
            if self.drop_states_mask is None:
                self.drop_states_mask = self._initialize_mask(
                    F, self.drop_states, states[0])
            states = [states[0] * self.drop_states_mask] + list(states[1:])
        if self.drop_inputs:
            if self.drop_inputs_mask is None:
                self.drop_inputs_mask = self._initialize_mask(
                    F, self.drop_inputs, inputs)
            inputs = inputs * self.drop_inputs_mask
        next_output, next_states = cell(inputs, states)
        if self.drop_outputs:
            if self.drop_outputs_mask is None:
                self.drop_outputs_mask = self._initialize_mask(
                    F, self.drop_outputs, next_output)
            next_output = next_output * self.drop_outputs_mask
        return next_output, next_states
