"""Contrib RNN cells (conv-RNN etc.) — Conv1DRNNCell family is a
round-2 item; VariationalDropoutCell ships now."""
from .rnn_cell import VariationalDropoutCell
