"""Contrib layers (python/mxnet/gluon/contrib/nn/basic_layers.py analog)."""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ...nn import Sequential, HybridSequential, BatchNorm, Embedding

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle2D"]


class Concurrent(Sequential):
    """Parallel application + concat (reference Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as nd
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.copy(x)


class SparseEmbedding(Embedding):
    """Embedding with row_sparse gradient (reference SparseEmbedding —
    Wide&Deep config). On XLA the backward is a scatter-add; the sparse
    kvstore row_id pull path consumes the touched-row set."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, **kwargs)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference contrib SyncBatchNorm over
    kvstore-like reduce). Under the sharded jit path, the mean/var
    reductions become cross-replica by construction (psum over the dp
    axis inserted by the partitioner), so this inherits plain BatchNorm
    eager semantics and documents the jit contract."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class PixelShuffle2D(HybridBlock):
    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        try:
            self._factors = (int(factor),) * 2
        except TypeError:
            self._factors = tuple(int(f) for f in factor)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        x = F.reshape(x, (0, -4, -1, f1 * f2, 0, 0))
        x = F.reshape(x, (0, 0, -4, f1, f2, 0, 0))
        x = F.transpose(x, (0, 1, 4, 2, 5, 3))
        x = F.reshape(x, (0, 0, -3, -3))
        return x
