from .basic_layers import (
    Concurrent, HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm,
    PixelShuffle2D,
)
