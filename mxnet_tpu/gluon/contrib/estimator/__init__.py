from .estimator import Estimator
from .event_handler import (
    TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin, BatchEnd,
    StoppingHandler, MetricHandler, ValidationHandler, LoggingHandler,
    CheckpointHandler, EarlyStoppingHandler,
)
