"""Estimator event handlers (gluon/contrib/estimator/event_handler.py)."""
from __future__ import annotations

import logging

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False


class MetricHandler(EpochBegin, BatchEnd):
    def __init__(self, train_metrics):
        self.train_metrics = train_metrics or []

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.train_metrics:
            metric.reset()


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period

    def epoch_end(self, estimator, *args, **kwargs):
        if self.epoch_period:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    def __init__(self, log_interval="epoch", metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics

    def epoch_end(self, estimator, epoch, *args, **kwargs):
        vals = [m.get() for m in estimator.train_metrics]
        msg = " ".join(f"{n}={v:.4f}" for n, v in vals)
        logging.info("Epoch[%d] %s", epoch, msg)


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5, resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.epoch_period = epoch_period

    def epoch_end(self, estimator, epoch=0, *args, **kwargs):
        import os
        os.makedirs(self.model_dir, exist_ok=True)
        path = os.path.join(self.model_dir,
                            f"{self.model_prefix}-epoch{epoch}.params")
        estimator.net.save_parameters(path)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.patience = patience
        self.wait = 0
        self.stopped_epoch = 0
