"""Estimator (gluon/contrib/estimator/estimator.py analog, v≥1.6):
high-level fit() over gluon blocks with event handlers."""
from __future__ import annotations

from .... import metric as metric_mod
from ....base import MXNetError
from ... import loss as gloss
from ...trainer import Trainer
from .event_handler import (
    TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin, BatchEnd,
    MetricHandler, LoggingHandler, StoppingHandler,
)

__all__ = ["Estimator"]


class Estimator:
    def __init__(self, net, loss, metrics=None, initializer=None,
                 trainer=None, context=None):
        from .... import autograd
        self._autograd = autograd
        self.net = net
        self.loss = loss if isinstance(loss, gloss.Loss) else loss
        self.train_metrics = metrics if isinstance(metrics, list) else \
            ([metrics] if metrics else [metric_mod.Accuracy()])
        from ....context import current_context
        self.context = context or [current_context()]
        if not isinstance(self.context, list):
            self.context = [self.context]
        if initializer is not None:
            net.initialize(initializer, ctx=self.context, force_reinit=False)
        else:
            try:
                net.collect_params().initialize(ctx=self.context)
            except Exception:
                pass
        self.trainer = trainer or Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 0.001})

    def evaluate(self, val_data, val_metrics=None):
        metrics = val_metrics or self.train_metrics
        for m in metrics:
            m.reset()
        for batch in val_data:
            data, label = batch[0], batch[1]
            data = data.as_in_context(self.context[0])
            label = label.as_in_context(self.context[0])
            pred = self.net(data)
            for m in metrics:
                m.update([label], [pred])
        return [m.get() for m in metrics]

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None,
            batches=None):
        import time

        from ....module.base_module import _fit_telemetry
        from ....telemetry import spans as _spans
        autograd = self._autograd
        handlers = event_handlers or []
        handlers.append(LoggingHandler())
        step_ms, samples_per_sec = _fit_telemetry("gluon_fit")
        for epoch in range(epochs):
            for m in self.train_metrics:
                m.reset()
            nbatch = 0
            # per-epoch span (tail-sampled local root) with per-step
            # children — the same tree shape as Module.fit
            with _spans.span("fit/epoch", loop="gluon_fit",
                             epoch=epoch) as ep_span:
                for batch in train_data:
                    data, label = batch[0], batch[1]
                    data = data.as_in_context(self.context[0])
                    label = label.as_in_context(self.context[0])
                    t0 = time.perf_counter()
                    with _spans.span("fit/step", step=nbatch):
                        with autograd.record():
                            pred = self.net(data)
                            loss = self.loss(pred, label)
                        loss.backward()
                        self.trainer.step(data.shape[0])
                    dt = time.perf_counter() - t0
                    step_ms.observe(dt * 1e3)
                    if dt > 0:
                        samples_per_sec.set(data.shape[0] / dt)
                    for m in self.train_metrics:
                        m.update([label], [pred])
                    nbatch += 1
                    if batches is not None and nbatch >= batches:
                        break
                ep_span.set_attr(batches=nbatch)
            for h in handlers:
                if isinstance(h, LoggingHandler):
                    h.epoch_end(self, epoch)
        return self
