"""Fused recurrent layers (python/mxnet/gluon/rnn/rnn_layer.py analog).

gluon.rnn.LSTM/GRU/RNN wrap the fused RNN op (ndarray/op_impl_rnn.py —
the cuDNN-RNN-analog lax.scan kernel). Parameter naming matches the
reference ({l,r}{i}_{i2h,h2h}_{weight,bias}) so checkpoints port.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ...ndarray import NDArray
from ...ndarray.register import invoke, get_op
from ... import autograd as _autograd
from ..block import HybridBlock
from ..parameter import tensor_types

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][:self._dir]:
                    self._register_param(f"{j}{i}_i2h_weight",
                                         (ng * nh, ni), i2h_weight_initializer)
                    self._register_param(f"{j}{i}_h2h_weight",
                                         (ng * nh, nh), h2h_weight_initializer)
                    self._register_param(f"{j}{i}_i2h_bias",
                                         (ng * nh,), i2h_bias_initializer)
                    self._register_param(f"{j}{i}_h2h_bias",
                                         (ng * nh,), h2h_bias_initializer)
                ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def infer_shape(self, x, *args):
        isz = x.shape[2] if self._layout == "TNC" else x.shape[2]
        ni = isz
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                getattr(self, f"{j}{i}_i2h_weight").shape = (ng * nh, ni)
            ni = nh * self._dir
        self._input_size = isz

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd
        if func is None:
            func = nd.zeros
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            info = dict(info)
            info.pop("__layout__", None)
            info.update(kwargs)
            states.append(func(name=f"{self.prefix}h0_{i}", **info))
        return states

    def __call__(self, inputs, states=None, sequence_length=None, **kwargs):
        self.skip_states = states is None
        if states is None:
            if isinstance(inputs, NDArray):
                batch_size = inputs.shape[self._layout.find("N")]
                states = self.begin_state(batch_size, ctx=inputs.ctx,
                                          dtype=str(inputs.dtype))
            else:
                raise MXNetError("states required for symbolic input")
        if isinstance(states, tensor_types):
            states = [states]
        return super().__call__(inputs, states)

    def forward(self, inputs, states):
        """Run the fused RNN op."""
        from ... import ndarray as nd

        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        # finalize deferred params
        try:
            flat = self._flat_params(inputs.ctx)
        except Exception:
            self.infer_shape(inputs)
            for _, p in self.params.items():
                p._finish_deferred_init()
            flat = self._flat_params(inputs.ctx)

        params = {"state_size": self._hidden_size,
                  "num_layers": self._num_layers,
                  "bidirectional": self._dir == 2,
                  "mode": self._mode, "p": self._dropout,
                  "state_outputs": True,
                  "_training": _autograd.is_training()}
        inputs_list = [inputs, flat, states[0]]
        if self._mode == "lstm":
            inputs_list.append(states[1])
        res = invoke(get_op("RNN"), inputs_list, params)
        if self._mode == "lstm":
            out, h, c = res
            out_states = [h, c]
        else:
            out, h = res
            out_states = [h]
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        return out if self.skip_states else (out, out_states)

    def _flat_params(self, ctx):
        """Pack per-layer params into the cuDNN-canonical flat vector."""
        from ... import ndarray as nd
        ws = []
        bs = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                ws.append(getattr(self, f"{j}{i}_i2h_weight").data(ctx).reshape(-1))
                ws.append(getattr(self, f"{j}{i}_h2h_weight").data(ctx).reshape(-1))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                bs.append(getattr(self, f"{j}{i}_i2h_bias").data(ctx))
                bs.append(getattr(self, f"{j}{i}_h2h_bias").data(ctx))
        return nd.concat(*(ws + bs), dim=0)

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        mapping = f"{self._input_size or None} -> {self._hidden_size}"
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)


class RNN(_RNNLayer):
    """Vanilla (Elman) multi-layer RNN with relu/tanh activation."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Fused multi-layer LSTM (the cuDNN-LSTM analog; WikiText-2 config)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", projection_size, **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
