"""Recurrent cells (python/mxnet/gluon/rnn/rnn_cell.py analog): the
step-at-a-time API (`cell(x_t, states)`) plus `unroll`. On TPU prefer
the fused layers (rnn_layer.py → lax.scan); cells exist for parity and
for custom recurrences — `unroll` is a Python loop that XLA fuses when
hybridized."""
from __future__ import annotations

from ...base import MXNetError
from ...ndarray import NDArray
from ..block import HybridBlock
from ..parameter import tensor_types

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    from ... import ndarray as nd
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    batch_size = 0
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            if length is None:
                length = inputs.shape[in_axis]
            inputs = nd.split(inputs, num_outputs=inputs.shape[in_axis],
                              axis=in_axis, squeeze_axis=True)
            if not isinstance(inputs, list):
                inputs = [inputs]
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = nd.stack(*[i.reshape((1,) + i.shape) for i in inputs],
                              axis=0).reshape((-1,) + inputs[0].shape)
            if axis == 1:
                inputs = inputs.swapaxes(0, 1)
    if isinstance(inputs, list):
        length = len(inputs)
    else:
        length = inputs.shape[axis]
    return inputs, axis, batch_size, length


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    from ... import ndarray as nd
    if not isinstance(data, list):
        return nd.SequenceMask(data, sequence_length=valid_length,
                               use_sequence_length=True, axis=time_axis)
    return data


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called directly. " \
            "Call the modifier cell instead."
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            info.pop("__layout__", None)
            info.update(kwargs)
            states.append(func(name=f"{self.prefix}begin_state_{self._init_counter}",
                               **info))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, batch_size, length = _format_sequence(
            length, inputs, layout, False)
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size, ctx=inputs[0].ctx if isinstance(inputs, list) else inputs.ctx)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        from ... import ndarray as nd
        if valid_length is not None:
            states = [nd.SequenceLast(nd.stack(*ele_list, axis=0),
                                      sequence_length=valid_length,
                                      use_sequence_length=True, axis=0)
                      for ele_list in zip(*all_states)]
            outputs = _mask_sequence_variable_length(nd, outputs, length,
                                                     valid_length, axis, True)
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell):
    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh",
                 recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._activation = activation
        self._recurrent_activation = recurrent_activation
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.split(gates, num_outputs=4, axis=1)
        in_gate = self._get_activation(F, slice_gates[0], self._recurrent_activation)
        forget_gate = self._get_activation(F, slice_gates[1], self._recurrent_activation)
        in_transform = self._get_activation(F, slice_gates[2], self._activation)
        out_gate = self._get_activation(F, slice_gates[3], self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h = F.split(h2h, num_outputs=3, axis=1)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h + reset_gate * h2h, act_type="tanh")
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert isinstance(rate, float)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified twice" \
            % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = self.base_cell, self.zoneout_outputs, self.zoneout_states
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output if self._prev_output is not None \
            else F.zeros_like(next_output)
        output = F.where(mask(p_outputs, next_output), next_output, prev_output) \
            if p_outputs != 0. else next_output
        new_states = [F.where(mask(p_states, new_s), new_s, old_s)
                      for new_s, old_s in zip(next_states, states)] \
            if p_states != 0. else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    def __init__(self, base_cell):
        super().__init__(base_cell)

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd
        self.reset()
        inputs, axis, batch_size, length = _format_sequence(length, inputs,
                                                            layout, False)
        begin_state = begin_state if begin_state is not None else \
            self.begin_state(batch_size, ctx=inputs[0].ctx)
        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        reversed_r_outputs = list(reversed(r_outputs))
        outputs = [nd.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, reversed_r_outputs)]
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
