from .rnn_cell import (
    RecurrentCell, HybridRecurrentCell, RNNCell, LSTMCell, GRUCell,
    SequentialRNNCell, DropoutCell, ZoneoutCell, ResidualCell,
    BidirectionalCell, ModifierCell,
)
from .rnn_layer import RNN, LSTM, GRU
