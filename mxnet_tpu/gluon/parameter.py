"""Gluon Parameter / ParameterDict / Constant
(python/mxnet/gluon/parameter.py analog).

Preserved semantics: deferred shape inference (shape with 0s finalized
at first forward), ``grad_req`` ('write'/'add'/'null'), per-context
replicas (``list_data``/``list_grad``), ``_reduce`` for multi-device
averaging, sharing via ParameterDict prefix/shared, ``row_sparse``
stype hooks. On a TPU slice, per-context replicas are per-chip copies
of one process; the sharded Trainer path keeps a single mesh-sharded
array instead (replicas collapse to views) — both live behind this API.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..base import MXNetError, dtype_np
from ..context import Context, cpu, current_context
from ..initializer import InitDesc, create as init_create
from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["DeferredInitializationError", "Parameter", "Constant", "ParameterDict",
           "tensor_types"]

tensor_types = (NDArray,)


class DeferredInitializationError(MXNetError):
    """Raised when accessing a parameter whose shape is not yet known."""


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data = None  # OrderedDict ctx→NDArray
        self._grad = None
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        self._grad_req = grad_req
        self._stype = stype
        self._grad_stype = grad_stype
        self._deferred_init = ()

    def __repr__(self):
        return f"Parameter {self.name} (shape={self._shape}, dtype={self.dtype})"

    # -- properties --------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data:
                for arr in self._data.values():
                    arr._grad = None
                    arr._grad_req = "null"
        elif self._data is not None:
            self._init_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        assert len(self._shape) == len(new_shape) and \
            all(j in (0, i) or i == j for i, j in zip(new_shape, self._shape)), \
            f"Expected shape {new_shape} is incompatible with given shape {self._shape}."
        self._shape = tuple(new_shape)

    @property
    def stype(self):
        return self._stype

    # -- initialization ----------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        if default_init is None:
            from ..initializer import Uniform
            default_init = Uniform()
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._shape is None or any(s == 0 for s in self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise MXNetError(
                f"Cannot initialize Parameter '{self.name}' because it has "
                f"invalid shape: {self._shape}.")
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        data = nd.zeros(self._shape, ctx=ctx[0], dtype=self.dtype)
        initializer = init_create(init) if init is not None else \
            (init_create(self.init) if self.init is not None else
             init_create(default_init) if isinstance(default_init, str) else default_init)
        initializer(InitDesc(self.name), data)
        # pin every replica to its context device with an explicit
        # device_put (copyto): initializer ops may have produced an
        # uncommitted array that the runtime placed on the DEFAULT
        # device (observed on TPU hosts: a cpu-ctx replica landing on
        # the chip, which silently declines the fused all-reduce path)
        self._data = OrderedDict((c, data.copyto(c)) for c in ctx)
        self._deferred_init = ()
        if self._grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        if self._shape is None or any(s == 0 for s in self._shape):
            raise DeferredInitializationError(
                f"Parameter '{self.name}' has not been initialized yet")
        self._finish_init(init, ctx, default_init)
        if data is not None:
            # set_data() was called while init was deferred — apply it
            self.set_data(data)

    def _init_grad(self):
        self._grad = OrderedDict(
            (c, nd.zeros(self._shape, ctx=c, dtype=self.dtype))
            for c in self._data)
        for c, arr in self._data.items():
            arr._grad = self._grad[c]
            arr._grad_req = self._grad_req
            arr._is_leaf = True

    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    f"Parameter '{self.name}' has not been initialized yet "
                    "because initialization was deferred. Actual "
                    "initialization happens during the first forward pass.")
            raise MXNetError(
                f"Parameter '{self.name}' has not been initialized. You "
                "should initialize parameters and create Trainer first.")

    # -- data access -------------------------------------------------------
    def data(self, ctx=None) -> NDArray:
        self._check_initialized(ctx)
        if ctx is None:
            return next(iter(self._data.values()))
        if ctx not in self._data:
            raise MXNetError(
                f"Parameter '{self.name}' was not initialized on context {ctx}. "
                f"It was only initialized on {list(self._data)}.")
        return self._data[ctx]

    def list_data(self):
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx=None) -> NDArray:
        self._check_initialized()
        if self._grad is None:
            raise MXNetError(
                f"Cannot get gradient array for Parameter '{self.name}' "
                "because grad_req='null'")
        if ctx is None:
            return next(iter(self._grad.values()))
        return self._grad[ctx]

    def list_grad(self):
        self._check_initialized()
        assert self._grad is not None
        return list(self._grad.values())

    def list_ctx(self):
        if self._data is None and self._deferred_init:
            return self._deferred_init[1]
        self._check_initialized()
        return list(self._data.keys())

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            assert self._deferred_init, \
                f"Parameter '{self.name}' has not been initialized"
            self._deferred_init = self._deferred_init[:3] + (data,)
            return
        for arr in self._data.values():
            arr._set_data(data._data if isinstance(data, NDArray) else data)

    def zero_grad(self):
        if self._grad is None:
            return
        for g in self._grad.values():
            g._set_data(nd.zeros_like(g)._data)

    def _reduce(self) -> NDArray:
        """Average value over contexts (for save_parameters)."""
        ctx = cpu()
        if self._stype == "default":
            block = self.list_data()
            if len(block) == 1:
                return block[0].copyto(ctx)
            out = block[0].copyto(ctx)
            for b in block[1:]:
                out += b.as_in_context(ctx)
            return out / len(block)
        return self.data().copyto(ctx)

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data:
            data = next(iter(self._data.values()))
            self._data = OrderedDict((c, data.as_in_context(c)) for c in ctx)
            if self._grad_req != "null":
                self._init_grad()
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        self._data = OrderedDict((c, a.astype(dtype)) for c, a in self._data.items())
        if self._grad is not None:
            self._init_grad()

    def var(self):
        from .. import symbol
        if self._var is None:
            self._var = symbol.var(self.name, shape=self.shape,
                                   dtype=self.dtype, lr_mult=self.lr_mult,
                                   wd_mult=self.wd_mult)
        return self._var

    def row_sparse_data(self, row_id):
        from ..ndarray import sparse
        dense = self.data()
        return sparse.cast_storage(dense, "row_sparse")

    def list_row_sparse_data(self, row_id):
        return [self.row_sparse_data(row_id)]


class Constant(Parameter):
    """Non-differentiable constant parameter."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class _ConstInit:
            def __call__(self, _, arr):
                value.copyto(arr)

            def dumps(self):
                import json
                return json.dumps(["constant", {"value": value.asnumpy().tolist()}])

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.dtype) if value.dtype != np.float32 else "float32",
                         init=_ConstInit(), differentiable=False)


class ParameterDict:
    """Ordered name→Parameter mapping with prefixing & sharing."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __repr__(self):
        s = "\n".join(f"  {v}" for v in self._params.values())
        return f"{type(self).__name__} '{self._prefix}' (\n{s}\n)"

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        # merge unknown dims
                        if len(v) == len(existing):
                            merged = tuple(ev if sv in (0, None) else sv
                                           for sv, ev in zip(v, existing))
                            param._shape = merged
                        continue
                elif v is not None:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError(f"No constant named '{name}'")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"Cannot update self with other because they "
                                 f"have different Parameters with the same name '{k}'")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        if init is None:
            from ..initializer import Uniform
            init = Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def list_ctx(self):
        s = set()
        for v in self.values():
            s.update(v.list_ctx() if v._data or v._deferred_init else [])
        return list(s)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            weight = param._reduce()
            if not param.name.startswith(strip_prefix):
                raise MXNetError(f"Prefix '{strip_prefix}' is to be striped "
                                 f"before saving, but Parameter's name "
                                 f"'{param.name}' does not start with it")
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        arg_dict = nd.load(filename)
        if not isinstance(arg_dict, dict):
            raise MXNetError(f"{filename} contains unnamed arrays")
        arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    f"Parameter '{name}' is missing in file '{filename}'"
        for name, arr in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError(
                        f"Parameter '{name}' loaded from file '{filename}' is "
                        "not present in ParameterDict")
                continue
            param = self._params[name]
            if param._data is None and param._deferred_init:
                param.shape = arr.shape
                param._finish_deferred_init()
            elif param._data is None:
                param._shape = arr.shape
                param.initialize(ctx=ctx or [current_context()])
            param.set_data(arr)
