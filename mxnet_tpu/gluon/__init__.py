"""Gluon — the imperative high-level API (python/mxnet/gluon analog)."""
from .parameter import Parameter, Constant, ParameterDict, DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock, functionalize
from .trainer import Trainer
from . import nn
from . import rnn
from . import loss
from .loss import SoftmaxCrossEntropyLoss, L2Loss, L1Loss
from . import data
from . import utils
from .utils import split_and_load, split_data, clip_global_norm
from . import model_zoo
from . import contrib
