"""Gluon Trainer (python/mxnet/gluon/trainer.py analog).

Same contract as the reference: created over a ParameterDict + optimizer,
``step(batch_size)`` = allreduce gradients across devices/workers
(KVStore path) then apply the optimizer; supports ``update_on_kvstore``,
gradient rescale, sparse row pulls, save/load of optimizer states.

TPU mapping (SURVEY §3.2): on one process the per-context replicas are
chips of a slice, so _allreduce_grads sums replica gradients (XLA lowers
sharded sums to ICI AllReduce); multi-host uses a Dist KVStore whose
reduce rides DCN. The fused-step fast path (whole train step in one XLA
computation) lives in parallel/spmd.py and the benchmarks use it.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt
from .. import kvstore as _kvstore_mod
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
            param._trainer = self
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore, "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = list(self._params)

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        if kvstore is None:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kv = kvstore if not isinstance(kvstore, str) \
                else _kvstore_mod.create(kvstore)
            self._kvstore = kv
            if kv.type == "horovod":
                # the allreduce-only store never runs the optimizer
                # (reference trainer.py horovod branch)
                if update_on_kvstore:
                    raise ValueError(
                        "Cannot set update_on_kvstore=True when kvstore "
                        "is 'horovod'")
                update_on_kvstore = False
            elif update_on_kvstore is None:
                update_on_kvstore = kv.num_workers > 1
            self._update_on_kvstore = update_on_kvstore
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        self._kv_initialized = True

    def _init_params(self):
        """Lazily register params with the kvstore once initialized."""
        pending = []
        for param in self._params_to_init:
            if param._deferred_init:
                pending.append(param)
                continue
            if self._kvstore is not None:
                idx = self._param2idx[param.name]
                self._kvstore.init(idx, param.data())
        self._params_to_init = pending

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _row_sparse_pull(self, parameter, out, row_id, full_idx=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._kvstore is not None:
            idx = self._param2idx[parameter.name]
            self._kvstore.row_sparse_pull(idx, out=out, row_ids=row_id)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads + update (reference Trainer.step)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "allreduce_grads() when parameters are updated on kvstore " \
            "is not supported. Try setting `update_on_kvstore` to False."
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            # no kvstore configured, but multi-replica params still need
            # the sum — otherwise _update's update-once-and-broadcast
            # would silently drop every other replica's gradient
            from ..ndarray.sparse import BaseSparseNDArray
            from ..parallel import comm
            pending = []
            for param in self._params:
                if param.grad_req == "null":
                    continue
                g = param.list_grad()
                if len(g) > 1:
                    if isinstance(g[0], BaseSparseNDArray):
                        # reference contract: multi-device row_sparse
                        # training REQUIRES a kvstore (sparse grads
                        # cannot ride the dense stacked reduce)
                        raise MXNetError(
                            f"Parameter '{param.name}' has row_sparse "
                            "gradients on multiple contexts; Trainer "
                            "needs a kvstore for sparse multi-device "
                            "training (kvstore=None was given)")
                    pending.append(g)
            if pending:
                comm.reduce_grad_ndarrays_inplace(pending)
            return
        if self._update_on_kvstore:
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    # push grads; optimizer runs in kvstore; pull weights
                    self._kvstore.push(i, param.list_grad())
            return
        # batch every key into ONE fused pushpull: the kvstore reduces the
        # whole gradient set in a single compiled XLA computation (the
        # kvstore_nccl.h fused-pushpull analog; bucketing is the
        # compiler's all-reduce combiner). Key order is the stable param
        # index order — identical on every worker by construction.
        keys, grads = [], []
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                g = param.list_grad()
                if len(g) > 1 or self._kvstore.num_workers > 1:
                    keys.append(i)
                    grads.append(g)
        if keys:
            self._kvstore.pushpull(keys, grads, out=grads)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "update() when parameters are updated on kvstore is not " \
            "supported. Try setting `update_on_kvstore` to False."
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        """Apply the optimizer ONCE per parameter (replica 0) and
        broadcast the new weight to the other replicas — gradients are
        identical after _allreduce_grads, so one update + copy keeps
        optimizer state/schedules exact (no shared-state mutation per
        replica) at the same traffic as a kvstore pull. Dense params
        batch into a single fused multi-tensor op
        (multi_sgd_* analog; Updater.update_multi)."""
        from ..ndarray.sparse import BaseSparseNDArray

        batch_idx, batch_w, batch_g, batch_bcast = [], [], [], []
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._update_on_kvstore and self._kvstore is not None:
                # weights now live in the kvstore; pull them back
                self._kvstore.pull(i, param.list_data(), ignore_sparse=False)
                continue
            datas, grads = param.list_data(), param.list_grad()
            if isinstance(grads[0], BaseSparseNDArray):
                # sparse updates keep the per-key path (rsp ops)
                self._updaters[0](i, grads[0], datas[0])
            else:
                batch_idx.append(i)
                batch_w.append(datas[0])
                batch_g.append(grads[0])
            batch_bcast.append((datas[0], datas[1:]))
        if batch_idx:
            self._updaters[0].update_multi(batch_idx, batch_g, batch_w)
        for src, rest in batch_bcast:
            for dst in rest:
                src.copyto(dst)

    def save_states(self, fname):
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
            # adopt the restored optimizer (it carries num_update /
            # index counts — resetting to the fresh one would restart
            # Adam bias correction and lr schedules)
            self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {i: p for i, p in
                                      enumerate(self._params)}
