"""Transformer layers: MultiHeadAttention, PositionwiseFFN, encoder.

No upstream-gluon analog (SURVEY §5.7: MXNet v1.x composes attention
from batch_dot+softmax in user code / GluonNLP, an external repo).
Built TPU-first: the no-mask path is one fused Pallas flash-attention
op per layer (mx.nd.flash_attention); masked attention (padding masks)
composes batch_dot+softmax exactly as the reference era did — the
flash kernel skips attention-prob dropout, standard for flash
implementations.

Layout convention: (batch, seq, units) inputs, post-LN residual blocks
(BERT) or pre-LN (``pre_norm=True``).
"""
from __future__ import annotations

import math

from .basic_layers import Activation, Dense, Dropout, LayerNorm
from ..block import HybridBlock

__all__ = ["MultiHeadAttention", "PositionwiseFFN",
           "TransformerEncoderCell", "TransformerEncoder"]


class MultiHeadAttention(HybridBlock):
    """Self-attention with fused QKV projection.

    Parameters
    ----------
    units : total model width C (= num_heads * head_dim)
    num_heads : number of attention heads
    attention_dropout : dropout on attention probs (masked path only)
    causal : apply a causal mask
    """

    def __init__(self, units, num_heads, attention_dropout=0.0,
                 use_bias=True, causal=False, weight_initializer=None,
                 bias_initializer="zeros", dtype="float32", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._heads = num_heads
        self._causal = causal
        self._attn_drop = attention_dropout
        with self.name_scope():
            self.qkv_proj = Dense(3 * units, flatten=False, use_bias=use_bias,
                                  dtype=dtype,
                                  weight_initializer=weight_initializer,
                                  bias_initializer=bias_initializer,
                                  prefix="qkv_")
            self.out_proj = Dense(units, flatten=False, use_bias=use_bias,
                                  dtype=dtype,
                                  weight_initializer=weight_initializer,
                                  bias_initializer=bias_initializer,
                                  prefix="out_")
            self.dropout = Dropout(attention_dropout) if attention_dropout else None

    def _split_heads(self, F, x):
        # (B, S, C) -> (B, H, S, D)
        x = F.reshape(x, shape=(0, 0, self._heads, -1))
        return F.transpose(x, axes=(0, 2, 1, 3))

    def hybrid_forward(self, F, x, mask=None, valid_length=None,
                       segment_ids=None):
        from ... import autograd as _autograd

        c = self._units
        qkv = self.qkv_proj(x)                       # (B, S, 3C)
        q = F.slice_axis(qkv, axis=-1, begin=0, end=c)
        k = F.slice_axis(qkv, axis=-1, begin=c, end=2 * c)
        v = F.slice_axis(qkv, axis=-1, begin=2 * c, end=3 * c)
        q = self._split_heads(F, q)
        k = self._split_heads(F, k)
        v = self._split_heads(F, v)

        # packed rows (io/packing.py): segment_ids (B, S) make attention
        # block-diagonal per sequence. The flash path needs the row's
        # used length too — derive it when the caller didn't pass one
        # (packers lay segments contiguously, so count-of-nonzero IS it)
        if segment_ids is not None and valid_length is None:
            valid_length = F.segment_valid_len(segment_ids)

        # the flash kernel has no attention-prob dropout; honour a
        # configured attention_dropout by taking the composed path while
        # training (trace-time decision — training mode is static).
        # valid_length (B,) padding and segment_ids packing stay ON the
        # flash path — the kernel masks both natively; only arbitrary
        # additive masks force the composed path.
        need_drop = bool(self._attn_drop) and _autograd.is_training()
        if mask is None and not need_drop:
            if segment_ids is not None:
                out = F.flash_attention(q, k, v, valid_length, segment_ids,
                                        causal=self._causal)
            elif valid_length is not None:
                out = F.flash_attention(q, k, v, valid_length,
                                        causal=self._causal)
            else:
                out = F.flash_attention(q, k, v, causal=self._causal)
        else:
            # composed batch_dot+softmax path (reference-era attention);
            # mask is additive, broadcastable to (B, 1|H, S, S)
            scale = 1.0 / math.sqrt(c // self._heads)
            scores = F.batch_dot_attention_scores(q, k) * scale
            if mask is not None:
                scores = F.broadcast_add(scores, mask)
            if valid_length is not None:
                scores = F.attention_length_mask(scores, valid_length)
            if segment_ids is not None:
                scores = F.attention_segment_mask(scores, segment_ids)
            if self._causal:
                scores = F.causal_mask_scores(scores)
            probs = F.softmax(scores, axis=-1)
            if valid_length is not None:
                # an all-masked row softmaxes to uniform — zero it so
                # the composed path matches the flash kernel's l==0
                # zeros for empty (valid_len == 0) examples
                probs = F.attention_zero_empty_rows(probs, valid_length)
            if segment_ids is not None:
                # same guard for packed PADDING rows (segment id 0)
                probs = F.attention_zero_pad_rows(probs, segment_ids)
            if self.dropout is not None:
                probs = self.dropout(probs)
            out = F.batch_dot_attention_apply(probs, v)

        out = F.transpose(out, axes=(0, 2, 1, 3))    # (B, S, H, D)
        out = F.reshape(out, shape=(0, 0, -1))       # (B, S, C)
        return self.out_proj(out)


class PositionwiseFFN(HybridBlock):
    """Dense(hidden, act) -> Dense(units) with dropout."""

    def __init__(self, units, hidden_size, activation="gelu", dropout=0.0,
                 weight_initializer=None, bias_initializer="zeros",
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ffn_1 = Dense(hidden_size, flatten=False, dtype=dtype,
                               weight_initializer=weight_initializer,
                               bias_initializer=bias_initializer,
                               prefix="ffn1_")
            self.act = Activation(activation)
            self.ffn_2 = Dense(units, flatten=False, dtype=dtype,
                               weight_initializer=weight_initializer,
                               bias_initializer=bias_initializer,
                               prefix="ffn2_")
            self.dropout = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.ffn_2(self.act(self.ffn_1(x)))
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class TransformerEncoderCell(HybridBlock):
    """One encoder layer: MHA + residual + LN, FFN + residual + LN."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 attention_dropout=0.0, activation="gelu", pre_norm=False,
                 causal=False, layer_norm_eps=1e-12,
                 weight_initializer=None, bias_initializer="zeros",
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.attention = MultiHeadAttention(
                units, num_heads, attention_dropout=attention_dropout,
                causal=causal, weight_initializer=weight_initializer,
                bias_initializer=bias_initializer, dtype=dtype,
                prefix="attn_")
            self.attn_ln = LayerNorm(epsilon=layer_norm_eps, prefix="attn_ln_")
            self.ffn = PositionwiseFFN(
                units, hidden_size, activation=activation, dropout=dropout,
                weight_initializer=weight_initializer,
                bias_initializer=bias_initializer, dtype=dtype, prefix="ffn_")
            self.ffn_ln = LayerNorm(epsilon=layer_norm_eps, prefix="ffn_ln_")
            self.dropout = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, mask=None, valid_length=None,
                       segment_ids=None):
        if self._pre_norm:
            h = self.attention(self.attn_ln(x), mask, valid_length,
                               segment_ids)
            if self.dropout is not None:
                h = self.dropout(h)
            x = x + h
            h = self.ffn(self.ffn_ln(x))
            return x + h
        h = self.attention(x, mask, valid_length, segment_ids)
        if self.dropout is not None:
            h = self.dropout(h)
        x = self.attn_ln(x + h)
        h = self.ffn(x)
        return self.ffn_ln(x + h)


class TransformerEncoder(HybridBlock):
    """Stack of encoder cells (+ optional final pre-norm LN)."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, attention_dropout=0.0, activation="gelu",
                 pre_norm=False, causal=False, layer_norm_eps=1e-12,
                 weight_initializer=None, bias_initializer="zeros",
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._pre_norm = pre_norm
        self.cells = []
        with self.name_scope():
            for i in range(num_layers):
                cell = TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout=dropout,
                    attention_dropout=attention_dropout,
                    activation=activation, pre_norm=pre_norm, causal=causal,
                    layer_norm_eps=layer_norm_eps,
                    weight_initializer=weight_initializer,
                    bias_initializer=bias_initializer, dtype=dtype,
                    prefix=f"layer{i}_")
                self.register_child(cell)
                self.cells.append(cell)
            self.final_ln = (LayerNorm(epsilon=layer_norm_eps, prefix="final_ln_")
                             if pre_norm else None)

    def hybrid_forward(self, F, x, mask=None, valid_length=None,
                       segment_ids=None):
        for cell in self.cells:
            x = cell(x, mask, valid_length, segment_ids)
        if self.final_ln is not None:
            x = self.final_ln(x)
        return x
