"""Basic neural network layers
(python/mxnet/gluon/nn/basic_layers.py + activations.py analog)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ...ndarray import NDArray
from ... import autograd as _autograd
from ..block import Block, HybridBlock, defer_aux_update
from ..parameter import Parameter

__all__ = [
    "Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
    "BatchNorm", "InstanceNorm", "LayerNorm", "GroupNorm", "Flatten",
    "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU", "ELU",
    "SELU", "Swish", "GELU", "SiLU", "Identity",
]


class Sequential(Block):
    """Stack of Blocks executed sequentially."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                f"All children of this Sequential layer '{self.prefix}' are "
                "HybridBlocks, so it is recommended to use HybridSequential "
                "for the best performance.", stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks; hybridizes into one XLA computation."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: out = act(dot(x, W.T) + b)
    (reference gluon nn.Dense over FullyConnected op)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._in_units = in_units
        self._flatten = flatten
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x, *args):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} -> "
                f"{shape[0] if shape else None}, "
                f"{'linear' if self.act is None else self.act._act_type})")


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return F.copy(x)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        grad_stype = "row_sparse" if sparse_grad else "default"
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, grad_stype=grad_stype)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class BatchNorm(HybridBlock):
    """Batch normalization with running statistics
    (reference src/operator/nn/batch_norm.cc + gluon BatchNorm).

    Running stats are aux states: updated in train mode, used in predict
    mode. Inside a hybridize trace the update flows out functionally
    (defer_aux_update) and is written back by the cached-op caller."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (channels,)

    def cast(self, dtype):
        if str(dtype) in ("float16", "bfloat16"):
            dtype = "float32"  # stats in fp32, as cudnn does
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        training = _autograd.is_training()
        if training and not self._use_global_stats:
            # fused train-mode BN: 2-pass forward, 2-pass hand-written
            # backward (op_impl_nn.BatchNormTrain) — the composed
            # mean/diff/var graph costs ~6 HBM-bound passes in autodiff
            out, mean, var = _bn_train_apply(F, x, gamma, beta,
                                             running_mean, self._kwargs)
            mean, var = F.stop_gradient(mean), F.stop_gradient(var)
            m = self._momentum
            defer_aux_update(self.running_mean,
                             running_mean * m + mean.astype(running_mean.dtype) * (1 - m))
            defer_aux_update(self.running_var,
                             running_var * m + var.astype(running_var.dtype) * (1 - m))
            return out
        return _bn_apply(F, x, gamma, beta, running_mean, running_var,
                         self._kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0] if self.gamma.shape else None
        return f"BatchNorm(axis={self._axis}, eps={self._epsilon}, " \
               f"momentum={self._momentum}, in_channels={in_channels})"


def _bn_apply(F, x, gamma, beta, mean, var, kwargs):
    from ...ndarray.register import invoke, get_op
    if isinstance(x, NDArray):
        return invoke(get_op("BatchNorm"), [x, gamma, beta, mean, var],
                      {"eps": kwargs["eps"], "momentum": kwargs["momentum"],
                       "fix_gamma": kwargs["fix_gamma"], "axis": kwargs["axis"]})
    return F.BatchNorm(x, gamma, beta, mean, var, **kwargs)


def _bn_train_apply(F, x, gamma, beta, running_mean, kwargs):
    # running_mean re-centers the one-pass variance (cancellation guard)
    from ...ndarray.register import invoke, get_op
    params = {"eps": kwargs["eps"], "axis": kwargs["axis"],
              "fix_gamma": kwargs["fix_gamma"]}
    if isinstance(x, NDArray):
        return invoke(get_op("BatchNormTrain"),
                      [x, gamma, beta, running_mean], params)
    return F.BatchNormTrain(x, gamma, beta, running_mean, **params)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get("gamma",
                                         grad_req="write" if scale else "null",
                                         shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta",
                                        grad_req="write" if center else "null",
                                        shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    """Layer normalization (reference src/operator/nn/layer_norm.cc —
    BERT-critical; the Pallas fused kernel backs the op on TPU)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get("gamma",
                                         grad_req="write" if scale else "null",
                                         shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta",
                                        grad_req="write" if center else "null",
                                        shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)

    def __repr__(self):
        return f"LayerNorm(axis={self._axis}, eps={self._epsilon})"


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get("gamma",
                                         grad_req="write" if scale else "null",
                                         shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta",
                                        grad_req="write" if center else "null",
                                        shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[1]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class Flatten(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.copy(x)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func_name = function if isinstance(function, str) else \
            getattr(function, "__name__", "custom")
        self._func = function

    def hybrid_forward(self, F, x, *args):
        if isinstance(self._func, str):
            return getattr(F, self._func)(x, *args)
        return self._func(F, x, *args)


# ----------------------------------------------------------------------
# activations (gluon/nn/activations.py)
# ----------------------------------------------------------------------
class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        self._act_type = activation
        super().__init__(prefix=prefix, params=params)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return f"LeakyReLU({self._alpha})"


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer="zeros", in_channels=1, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(in_channels,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._approx = approximation != "erf"

    def hybrid_forward(self, F, x):
        return F.gelu(x, approximate=self._approx)


class Swish(HybridBlock):
    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return F.swish(x, beta=self._beta)


class SiLU(Swish):
    pass
