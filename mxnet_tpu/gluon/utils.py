"""Gluon utilities (python/mxnet/gluon/utils.py analog):
split_data / split_and_load / clip_global_norm."""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..context import Context
from ..ndarray import NDArray, array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "shape_is_known"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}.")
    if num_slice == 1:
        return [data]
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split batch and load each slice onto one context (the reference's
    per-GPU scatter; on a sharded TPU setup prefer the Trainer's mesh
    path which shards without host-side splitting)."""
    if not isinstance(data, NDArray):
        data = array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the concatenated L2 norm ≤ max_norm."""
    assert len(arrays) > 0
    ctx = arrays[0].ctx
    total = 0.0
    for arr in arrays:
        n = arr.norm()
        total += float((n * n).asscalar())
    total = np.sqrt(total)
    if check_isfinite and not np.isfinite(total):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def shape_is_known(shape):
    if shape is None:
        return False
    return all(s > 0 for s in shape)


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise MXNetError("network access is unavailable in the TPU sandbox")
