from . import vision
from .vision import get_model
from . import bert
from .bert import (BERTModel, BERTMLMHead, BERTNSPHead, bert_base,
                   bert_large, get_bert)
