from . import vision
from .vision import get_model
from . import bert
from .bert import (BERTModel, BERTMLMHead, BERTNSPHead, bert_base,
                   bert_large, bert_serving_entry, get_bert)
from . import wide_deep as wide_deep_zoo
from .wide_deep import WideDeep, wide_deep
