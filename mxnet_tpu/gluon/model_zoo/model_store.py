"""Local pretrained-weight store
(python/mxnet/gluon/model_zoo/model_store.py analog).

The reference resolves a model name to ``{name}-{sha1[:8]}.params`` in
a local root, verifies the SHA-1, and downloads on miss. This
environment has zero egress, so the TPU-native store is LOCAL-ONLY:
weights enter the store explicitly (``publish_model_file`` — e.g. from
a converted checkpoint on shared storage via the filesystem layer),
the hash registry persists next to the files (``model_index.json``),
and ``get_model_file`` resolves + verifies exactly like the reference.
A miss raises with the publish instructions instead of downloading.

Root resolution order: explicit ``root`` arg → $MXNET_TPU_MODEL_STORE →
$MXNET_HOME/models → ~/.mxnet/models (the reference default).
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil

from ...base import MXNetError

__all__ = ["get_model_file", "publish_model_file", "purge"]

_INDEX = "model_index.json"


def _default_root():
    from ... import envvars
    env = envvars.get("MXNET_TPU_MODEL_STORE")
    if env:
        return env
    home = os.environ.get("MXNET_HOME")
    if home:
        return os.path.join(home, "models")
    return os.path.join("~", ".mxnet", "models")


def _load_index(root):
    path = os.path.join(root, _INDEX)
    if os.path.isfile(path):
        with open(path) as f:
            return json.load(f)
    return {}


def _save_index(root, index):
    with open(os.path.join(root, _INDEX), "w") as f:
        json.dump(index, f, indent=1, sort_keys=True)


def _sha1(path):
    h = hashlib.sha1()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def short_hash(name, root=None):
    """First 8 sha1 chars of the stored file for ``name`` (the
    reference's filename stamp)."""
    root = os.path.expanduser(root or _default_root())
    index = _load_index(root)
    if name not in index:
        raise ValueError(f"{name} is not present in the model store "
                         f"at {root}")
    return index[name]["sha1"][:8]


def get_model_file(name, root=None):
    """Path to the verified ``{name}-{sha1[:8]}.params`` file.

    Exact reference contract minus the download: if the file exists and
    its SHA-1 matches the index, return it; if it exists but mismatches,
    raise (corruption is never silently loaded); if absent, raise with
    the local-publish instructions.
    """
    root = os.path.expanduser(root or _default_root())
    index = _load_index(root)
    if name in index:
        # an indexed name NEVER falls through to the unverified bare
        # file: a missing/corrupt indexed file is an error, not a
        # silent downgrade
        entry = index[name]
        fname = os.path.join(root, entry["file"])
        if not os.path.isfile(fname):
            raise MXNetError(
                f"the model store index at {root} names {entry['file']} "
                f"for {name!r} but the file is gone — re-publish it "
                "with publish_model_file")
        if _sha1(fname) != entry["sha1"]:
            raise MXNetError(
                f"checksum mismatch for {fname} (expected "
                f"{entry['sha1']}); the stored weights are corrupt — "
                "re-publish them with publish_model_file")
        return fname
    # un-indexed fallback: a bare {name}.params dropped into the root
    # (no hash recorded anywhere, so nothing to verify against — the
    # reference behaves the same for hand-placed files)
    bare = os.path.join(root, f"{name}.params")
    if os.path.isfile(bare):
        return bare
    raise MXNetError(
        f"pretrained weights for {name!r} are not in the local model "
        f"store at {root} and cannot be downloaded (zero-egress "
        "environment). Publish them once with\n"
        f"  mxnet_tpu.gluon.model_zoo.model_store.publish_model_file("
        f"{name!r}, '/path/to/{name}.params')\n"
        f"or drop a {name}.params file into {root}.")


def publish_model_file(name, path, root=None):
    """Copy ``path`` into the store as ``{name}-{sha1[:8]}.params`` and
    record its hash in the index. Returns the stored path."""
    root = os.path.expanduser(root or _default_root())
    os.makedirs(root, exist_ok=True)
    if not os.path.isfile(path):
        raise MXNetError(f"no weights file at {path}")
    sha = _sha1(path)
    fname = f"{name}-{sha[:8]}.params"
    dst = os.path.join(root, fname)
    if os.path.abspath(path) != os.path.abspath(dst):
        shutil.copyfile(path, dst)
    index = _load_index(root)
    prev = index.get(name)
    index[name] = {"file": fname, "sha1": sha}
    _save_index(root, index)
    if prev and prev["file"] != fname:
        # re-publish repoints the index — drop the orphaned old file
        old = os.path.join(root, prev["file"])
        if os.path.isfile(old):
            os.remove(old)
    return dst


def load_pretrained(net, name, ctx=None, root=None):
    """Resolve ``name`` in the store and load the verified weights into
    ``net`` (the shared tail of every model-zoo ``pretrained=True``)."""
    net.load_parameters(get_model_file(name, root=root), ctx=ctx)
    return net


def purge(root=None):
    """Remove every stored .params file and the index (reference
    model_store.purge)."""
    root = os.path.expanduser(root or _default_root())
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params") or f == _INDEX:
            os.remove(os.path.join(root, f))
