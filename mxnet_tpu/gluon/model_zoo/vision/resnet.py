"""ResNet V1/V2 (python/mxnet/gluon/model_zoo/vision/resnet.py analog).

The ImageNet north-star model (BASELINE config #2). Structure matches
the reference model zoo (BasicBlockV1/V2, BottleneckV1/V2, thumbnail
mode for CIFAR) so checkpoints and layer names line up.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "SpaceToDepthStem", "get_resnet",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
           "resnet101_v2", "resnet152_v2"]


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


class SpaceToDepthStem(HybridBlock):
    """TPU-first stem: numerically EXACT reformulation of the ImageNet
    ``Conv2D(channels, 7, strides=2, padding=3)`` stem as a 2x2
    space-to-depth followed by a 4x4 stride-1 conv over ``4*C`` input
    channels (the MLPerf ResNet TPU trick). The plain stem wastes MXU
    lanes (3 input channels, stride-2 access pattern); after
    space-to-depth the conv is dense and stride-1.

    The learnable parameter keeps the reference shape
    ``(channels, in_channels, 7, 7)`` so checkpoints interchange with
    the plain Conv2D stem; the 4x4x(4C) kernel is derived in-graph:
    pad the 7x7 taps to 8x8 at the front (tap k maps to offset pair
    ``((k+1)//2, (k+1)%2)``), then a reshape/transpose groups taps by
    parity to match ``space_to_depth``'s ``(dy*2+dx)*C + c`` channel
    packing. The asymmetric spatial pad (2 low, 1 high) reproduces the
    original pad-3 window. Beyond-reference extension (upstream has no
    such stem); exactness pinned by tests/test_gluon.py."""

    def __init__(self, channels, in_channels=3, weight_initializer=None,
                 **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(channels, in_channels, 7, 7),
                init=weight_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        # deferred init parity with the plain Conv2D stem: in_channels
        # comes from the data
        self._in_channels = x.shape[1]
        self.weight.shape = (self._channels, x.shape[1], 7, 7)

    def hybrid_forward(self, F, x, weight):
        o = self._channels
        wshp = getattr(weight, "shape", None)
        c = (wshp[1] if wshp and isinstance(wshp[1], int) and wshp[1] > 0
             else self._in_channels)
        shp = getattr(x, "shape", None)
        if shp and len(shp) == 4 and isinstance(shp[2], int) \
                and (shp[2] % 2 or shp[3] % 2):
            raise ValueError(
                "SpaceToDepthStem requires even H and W (2x2 "
                f"space-to-depth); got {shp} — use stem='conv' for odd "
                "input sizes")
        wp = F.pad(weight, mode="constant",
                   pad_width=(0, 0, 0, 0, 1, 0, 1, 0))
        w2 = wp.reshape((o, c, 4, 2, 4, 2)) \
               .transpose((0, 3, 5, 1, 2, 4)) \
               .reshape((o, 4 * c, 4, 4))
        y = F.space_to_depth(x, block_size=2)
        y = F.pad(y, mode="constant", pad_width=(0, 0, 0, 0, 2, 1, 2, 1))
        return F.Convolution(y, w2, None, kernel=(4, 4), stride=(1, 1),
                             pad=(0, 0), num_filter=o, no_bias=True)


def _stem_conv(channels, stem):
    if stem == "s2d":
        # in_channels=0 -> deferred init infers from data (parity with
        # the plain Conv2D stem on non-RGB inputs)
        return SpaceToDepthStem(channels, in_channels=0)
    return nn.Conv2D(channels, 7, 2, 3, use_bias=False)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1, use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


def _mark_remat(stage, policy=None):
    """Flag every residual block of a stage for trace-time activation
    recompute (jax.checkpoint wraps each block when the net is traced —
    see HybridBlock._remat_trace). active=False keeps imperative/
    CachedOp behavior unchanged; only traced training steps see it.
    ``policy``: optional jax.checkpoint_policies selector (e.g.
    "names:conv_out" saves conv outputs, recomputing only BN/relu)."""
    for blk in stage._children.values():
        blk.hybridize(active=False, remat=True, remat_policy=policy)


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 stem="conv", remat_stages=(), remat_policy=None, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(_stem_conv(channels[0], stem))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                stage = self._make_layer(block, num_layer,
                                         channels[i + 1], stride,
                                         i + 1,
                                         in_channels=channels[i])
                if (i + 1) in remat_stages:
                    _mark_remat(stage, remat_policy)
                self.features.add(stage)
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0):
        layer = nn.HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 stem="conv", remat_stages=(), remat_policy=None, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(_stem_conv(channels[0], stem))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                stage = self._make_layer(block, num_layer,
                                         channels[i + 1], stride,
                                         i + 1, in_channels=in_channels)
                if (i + 1) in remat_stages:
                    _mark_remat(stage, remat_policy)
                self.features.add(stage)
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0):
        layer = nn.HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None,
               root=None, **kwargs):
    assert num_layers in resnet_spec, \
        f"Invalid number of layers: {num_layers}. Options are {sorted(resnet_spec)}"
    block_type, layers, channels = resnet_spec[num_layers]
    assert 1 <= version <= 2, f"Invalid resnet version: {version}."
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, f"resnet{num_layers}_v{version}", ctx=ctx,
                        root=root)
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
