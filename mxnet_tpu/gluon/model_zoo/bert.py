"""BERT model family (the BASELINE config #3 flagship).

The reference-era BERT lives in GluonNLP (external repo, composed from
batch_dot+softmax primitive ops — SURVEY §6); here it is a first-class
model-zoo member built on the fused TransformerEncoder
(gluon/nn/transformer.py → Pallas flash attention + fused LayerNorm).

API mirrors GluonNLP's BERTModel: ``model(inputs, token_types)`` →
(sequence_output, pooled_output); MLM/NSP heads are separate blocks so
pretraining and fine-tuning share the trunk.
"""
from __future__ import annotations

from ... import initializer as init
from ..block import HybridBlock
from ..nn import Dense, Dropout, Embedding, LayerNorm, TransformerEncoder
from ..nn.basic_layers import Activation

__all__ = ["BERTModel", "BERTMLMHead", "BERTNSPHead", "bert_base", "bert_large",
           "get_bert", "bert_serving_entry"]


class BERTEmbeddings(HybridBlock):
    """token + position + segment embeddings, LN, dropout."""

    def __init__(self, vocab_size, units, max_length, token_types=2,
                 dropout=0.1, layer_norm_eps=1e-12, dtype="float32",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._max_length = max_length
        with self.name_scope():
            self.word_embed = Embedding(vocab_size, units, dtype=dtype,
                                        prefix="word_")
            self.token_type_embed = Embedding(token_types, units, dtype=dtype,
                                              prefix="type_")
            self.position_embed = Embedding(max_length, units, dtype=dtype,
                                            prefix="pos_")
            self.ln = LayerNorm(epsilon=layer_norm_eps, prefix="ln_")
            self.dropout = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, inputs, token_types, positions=None):
        # positions 0..S-1 derived from the input itself (jit-static)
        # unless the caller supplies explicit per-token positions — the
        # packed path does: each packed sequence's positions restart at
        # 0 (io/packing.py), not at its row offset. Embedding's take()
        # clips out-of-range ids, which would silently alias every
        # position past max_length — reject instead.
        try:
            seq_len = inputs.shape[1]
        except Exception:
            seq_len = None
        if positions is None and seq_len is not None \
                and seq_len > self._max_length:
            raise ValueError(
                f"sequence length {seq_len} exceeds max_length "
                f"{self._max_length} of the position table")
        x = self.word_embed(inputs) + self.token_type_embed(token_types)
        if positions is None:
            pos = F.arange_like(inputs, axis=1)
            x = x + F.expand_dims(self.position_embed(pos), 0)
        else:
            # caller contract: every position id < max_length (packers
            # bound ids by each SAMPLE's length, so keep packed sample
            # lengths <= max_length even when rows are longer).
            # Concrete (eager) positions are validated here; traced
            # values cannot be (take() would clip silently — the same
            # aliasing the seq_len guard above rejects).
            try:
                pmax = int(positions.asnumpy().max())
            except Exception:
                pmax = None
            if pmax is not None and pmax >= self._max_length:
                raise ValueError(
                    f"position id {pmax} exceeds the position table "
                    f"(max_length {self._max_length}); packed samples "
                    "must each be at most max_length tokens")
            x = x + self.position_embed(positions)
        x = self.ln(x)
        if self.dropout is not None:
            x = self.dropout(x)
        return x


class BERTModel(HybridBlock):
    """Trunk: embeddings → TransformerEncoder → (seq_out, pooled_out)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 token_types=2, dropout=0.1, attention_dropout=0.1,
                 layer_norm_eps=1e-12, use_pooler=True, dtype="float32",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.units = units
        self.vocab_size = vocab_size
        with self.name_scope():
            self.embeddings = BERTEmbeddings(
                vocab_size, units, max_length, token_types=token_types,
                dropout=dropout, layer_norm_eps=layer_norm_eps, dtype=dtype,
                prefix="embed_")
            self.encoder = TransformerEncoder(
                num_layers, units, hidden_size, num_heads, dropout=dropout,
                attention_dropout=attention_dropout, activation="gelu",
                pre_norm=False, layer_norm_eps=layer_norm_eps, dtype=dtype,
                prefix="enc_")
            self.pooler = (Dense(units, flatten=False, activation="tanh",
                                 dtype=dtype, prefix="pooler_")
                           if use_pooler else None)

    def hybrid_forward(self, F, inputs, token_types, valid_length=None,
                       mask=None, segment_ids=None, positions=None):
        """``valid_length`` (B,) per-example token counts — third
        positional input, matching the GluonNLP BERTModel signature
        (inputs, token_types, valid_length); rides the flash kernel's
        native per-row kv-length path. ``mask`` stays the general
        additive escape hatch (composed attention).

        Packed batches (io/packing.py) pass ``segment_ids`` (B, S) —
        attention goes block-diagonal per packed sequence — and
        ``positions`` (B, S), the per-segment position ids (each
        sequence's positional embedding restarts at 0). With packing
        the pooled output is meaningless (row slot 0 is only the FIRST
        packed sequence's [CLS]); slice per-segment outputs with the
        packer's placements instead."""
        x = self.embeddings(inputs, token_types, positions)
        seq = self.encoder(x, mask, valid_length, segment_ids)
        if self.pooler is None:
            return seq
        pooled = self.pooler(F.slice_axis(seq, axis=1, begin=0, end=1)
                             .reshape((0, -1)))
        return seq, pooled


class BERTMLMHead(HybridBlock):
    """transform (dense+gelu+LN) then decode to vocab logits."""

    def __init__(self, vocab_size, units, layer_norm_eps=1e-12,
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.transform = Dense(units, flatten=False, dtype=dtype,
                                   prefix="transform_")
            self.act = Activation("gelu")
            self.ln = LayerNorm(epsilon=layer_norm_eps, prefix="ln_")
            self.decoder = Dense(vocab_size, flatten=False, dtype=dtype,
                                 prefix="decoder_")

    def hybrid_forward(self, F, seq):
        return self.decoder(self.ln(self.act(self.transform(seq))))


class BERTNSPHead(HybridBlock):
    def __init__(self, dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.classifier = Dense(2, flatten=False, dtype=dtype,
                                    prefix="cls_")

    def hybrid_forward(self, F, pooled):
        return self.classifier(pooled)


_BERT_SPECS = {
    "bert_base": dict(units=768, hidden_size=3072, num_layers=12,
                      num_heads=12),
    "bert_large": dict(units=1024, hidden_size=4096, num_layers=24,
                       num_heads=16),
}


def get_bert(spec="bert_base", vocab_size=30522, max_length=512,
             dropout=0.1, dtype="float32", **kwargs):
    cfg = dict(_BERT_SPECS[spec])
    cfg.update(kwargs)
    return BERTModel(vocab_size=vocab_size, max_length=max_length,
                     dropout=dropout, attention_dropout=dropout,
                     dtype=dtype, **cfg)


def bert_base(**kwargs):
    """BERT-base (L=12, H=768, A=12) — the v5p north-star config."""
    return get_bert("bert_base", **kwargs)


def bert_large(**kwargs):
    return get_bert("bert_large", **kwargs)


def bert_serving_entry(model, head=None, hybridize=True):
    """Adapt a (initialized) BERT trunk to the ``ServingEngine`` model
    contract: ``entry(ids, token_types, valid_length, segment_ids,
    positions) -> (B, S, U)`` per-token outputs on packed rows.

    The packed pooled output is meaningless (row slot 0 is only the
    first packed sequence's [CLS]) so only the sequence output rides;
    the engine slices per-request outputs by placement and pools
    per SEGMENT (``pool="cls"/"mean"``) — the packed-correct analog of
    the pooler. ``head`` (e.g. a scorer Dense/BERTMLMHead) applies to
    the sequence output inside the same traced graph. ``hybridize``
    activates the CachedOp so each (rows, row_len) shape bucket
    compiles once and is cached — the serving fast path.
    """
    if hybridize:
        model.hybridize()
        if head is not None:
            head.hybridize()

    def entry(ids, token_types, valid_length, segment_ids, positions):
        out = model(ids, token_types, valid_length, None, segment_ids,
                    positions)
        seq = out[0] if isinstance(out, (list, tuple)) else out
        return head(seq) if head is not None else seq

    return entry
