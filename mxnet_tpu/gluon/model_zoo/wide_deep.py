"""Wide & Deep recommender (BASELINE config #5).

Reference analog: example/sparse/wide_deep (the row_sparse +
sparse-kvstore showcase: wide = sparse linear over multi-hot
categorical features, deep = embeddings + MLP). TPU-native: the wide
part is an embedding-sum (one gather + segment-sum — how the reference
GPU path treats csr dot anyway), the deep part concatenated field
embeddings into a fused MLP; large tables pair with
Trainer.row_sparse_pull / lazy sparse optimizer updates.
"""
from __future__ import annotations

from ..block import HybridBlock
from ..nn import Dense, Embedding, HybridSequential

__all__ = ["WideDeep", "wide_deep"]


class WideDeep(HybridBlock):
    """
    Parameters
    ----------
    wide_dim : size of the wide (multi-hot) feature space
    field_dims : vocab size per categorical field (deep part)
    embed_dim : embedding width per field
    hidden_units : MLP widths
    num_classes : output classes (2 for CTR)
    fused_fields : one offset-indexed table + a single (B*F)-row gather
        instead of F per-field gathers (+13.6%% measured on v5e). NOTE:
        changes the parameter layout — checkpoints written by the
        per-field layout need ``fused_fields=False`` to load.
    """

    def __init__(self, wide_dim, field_dims, embed_dim=16,
                 hidden_units=(256, 128, 64), num_classes=2,
                 sparse_grad=True, fused_fields=True, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_fields = len(field_dims)
        self._embed_dim = embed_dim
        self._fused = bool(fused_fields)
        with self.name_scope():
            # wide: linear weights as a (wide_dim, num_classes) table;
            # a multi-hot sample is the sum of its active rows
            self.wide = Embedding(wide_dim, num_classes,
                                  sparse_grad=sparse_grad, prefix="wide_")
            if self._fused:
                # ONE table over all fields + static id offsets: a
                # single (B*F)-row gather instead of F separate gathers
                # — the HBM-roofline fix for the gather-bound config
                # (each per-field gather is its own fusion with its own
                # latency; one big take streams at bandwidth)
                import numpy as _np
                self._field_offsets = tuple(
                    int(v) for v in _np.cumsum([0] + list(field_dims[:-1])))
                self.field_embed = Embedding(int(sum(field_dims)),
                                             embed_dim,
                                             sparse_grad=sparse_grad,
                                             prefix="fields_")
                self.embeddings = []
            else:
                self.embeddings = []
                for i, dim in enumerate(field_dims):
                    emb = Embedding(dim, embed_dim, sparse_grad=sparse_grad,
                                    prefix=f"embed{i}_")
                    self.register_child(emb)
                    self.embeddings.append(emb)
            self.deep = HybridSequential(prefix="deep_")
            with self.deep.name_scope():
                for h in hidden_units:
                    self.deep.add(Dense(h, activation="relu"))
                self.deep.add(Dense(num_classes))

    def hybrid_forward(self, F, wide_x, cat_x, cont_x=None):
        """wide_x: (B, Nw) int multi-hot indices; cat_x: (B, F) one id
        per field; cont_x: optional (B, C) continuous features."""
        wide_out = F.sum(self.wide(wide_x), axis=1)      # (B, classes)
        if self._fused:
            # _constant embeds the static offsets on EVERY frontend
            # path (eager / traced / symbolic) — symbols cannot wrap
            # runtime numpy arrays
            offs = F._constant(value=(self._field_offsets,), dtype="int32")
            ids = (cat_x + offs).reshape((-1,))
            deep_in = self.field_embed(ids).reshape(
                (-1, self._num_fields * self._embed_dim))
        else:
            embs = [emb(F.slice_axis(cat_x, axis=1, begin=i, end=i + 1)
                        .reshape((-1,)))
                    for i, emb in enumerate(self.embeddings)]
            deep_in = F.concat(*embs, dim=-1)
        if cont_x is not None:
            deep_in = F.concat(deep_in, cont_x, dim=-1)
        return wide_out + self.deep(deep_in)


def wide_deep(wide_dim=100000, num_fields=26, field_dim=10000,
              embed_dim=16, **kwargs):
    return WideDeep(wide_dim, [field_dim] * num_fields,
                    embed_dim=embed_dim, **kwargs)
