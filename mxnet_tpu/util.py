"""Misc utilities (python/mxnet/util.py analog)."""
from __future__ import annotations

import functools

__all__ = ["is_np_array", "is_np_shape", "set_np", "reset_np", "makedirs", "use_np"]

_NUMPY_ARRAY = False
_NUMPY_SHAPE = False


def is_np_array() -> bool:
    """Whether the numpy-semantics array mode is active (mx.npx.set_np).
    The TPU frontend keeps classic NDArray semantics by default."""
    return _NUMPY_ARRAY


def is_np_shape() -> bool:
    return _NUMPY_SHAPE


def set_np(shape=True, array=True):
    global _NUMPY_ARRAY, _NUMPY_SHAPE
    _NUMPY_ARRAY, _NUMPY_SHAPE = bool(array), bool(shape)


def reset_np():
    set_np(False, False)


def use_np(func):
    return func


def makedirs(d):
    import os
    os.makedirs(d, exist_ok=True)


def getenv(name, default=None):
    import os
    return os.environ.get(name, default)
