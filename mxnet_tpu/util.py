"""Misc utilities (python/mxnet/util.py analog)."""
from __future__ import annotations

import functools

__all__ = ["is_np_array", "is_np_shape", "set_np", "reset_np", "makedirs",
           "use_np", "np_scope"]

_NUMPY_ARRAY = False
_NUMPY_SHAPE = False


def is_np_array() -> bool:
    """Whether the numpy-semantics array mode is active (mx.npx.set_np).
    The TPU frontend keeps classic NDArray semantics by default."""
    return _NUMPY_ARRAY


def is_np_shape() -> bool:
    return _NUMPY_SHAPE


def set_np(shape=True, array=True):
    global _NUMPY_ARRAY, _NUMPY_SHAPE
    _NUMPY_ARRAY, _NUMPY_SHAPE = bool(array), bool(shape)


def reset_np():
    set_np(False, False)


class np_scope:
    """Context manager: numpy semantics active inside, previous mode
    restored on exit (python/mxnet/util.py use_np_array/use_np_shape
    scoped form)."""

    def __enter__(self):
        global _NUMPY_ARRAY, _NUMPY_SHAPE
        self._saved = (_NUMPY_ARRAY, _NUMPY_SHAPE)
        set_np()
        return self

    def __exit__(self, *exc):
        global _NUMPY_ARRAY, _NUMPY_SHAPE
        _NUMPY_ARRAY, _NUMPY_SHAPE = self._saved
        return False


def use_np(func):
    """Decorator: run ``func`` — or the entry methods of a class
    (``__init__``/``__call__``/``forward``/``hybrid_forward``) — with
    numpy semantics active, restoring the previous mode afterwards
    (python/mxnet/util.py ``use_np``)."""
    import inspect

    if inspect.isclass(func):
        for name in ("__init__", "__call__", "forward", "hybrid_forward"):
            m = func.__dict__.get(name)
            if m is not None and callable(m):
                setattr(func, name, use_np(m))
        return func

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with np_scope():
            return func(*args, **kwargs)

    return wrapped


def makedirs(d):
    import os
    os.makedirs(d, exist_ok=True)


def getenv(name, default=None):
    import os
    return os.environ.get(name, default)
