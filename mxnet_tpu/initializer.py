"""Weight initializers (python/mxnet/initializer.py analog).

Same registry + ``InitDesc``-pattern dispatch as the reference: an
Initializer is called with a descriptor (name) and the array to fill;
name patterns route to bias/gamma/beta defaults exactly like
``Initializer.__call__`` does upstream.
"""
from __future__ import annotations

import math
import re

import numpy as np

from .base import _Registry
from . import random as _random

__all__ = [
    "Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
    "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias", "Mixed",
    "InitDesc", "register", "create",
]

_REG = _Registry("initializer")
register = _REG.register


class InitDesc(str):
    """Name descriptor with optional attrs (reference InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init = desc.attrs.get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        else:
            self._init_default(desc, arr)

    # helpers write through the NDArray in-place API
    def _set(self, arr, value):
        import jax.numpy as jnp
        arr._set_data(jnp.asarray(np.asarray(value), arr.dtype))

    def _init_zero(self, _, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_one(self, _, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_bias(self, _, arr):
        self._init_zero(_, arr)

    def _init_gamma(self, _, arr):
        self._init_one(_, arr)

    def _init_beta(self, _, arr):
        self._init_zero(_, arr)

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)

    def dumps(self):
        import json
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


def _np_rng():
    # derive a numpy RNG from the global key chain so mx.random.seed works
    key = _random._next_key()
    return np.random.default_rng(np.asarray(key, dtype=np.uint32))


@register("zeros")
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._init_zero(_, arr)


@register("ones")
class One(Initializer):
    def _init_weight(self, _, arr):
        self._init_one(_, arr)


@register("constant")
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        self._set(arr, np.full(arr.shape, self.value))


@register("uniform")
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._set(arr, _np_rng().uniform(-self.scale, self.scale, arr.shape))


@register("normal")
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._set(arr, _np_rng().normal(0.0, self.sigma, arr.shape))


@register("orthogonal")
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        rng = _np_rng()
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = rng.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = rng.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        self._set(arr, self.scale * q.reshape(arr.shape))


@register("xavier")
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError(f"Xavier requires ndim>=2, got shape {shape} for {desc}")
        if len(shape) > 2:
            hw_scale = float(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        rng = _np_rng()
        if self.rnd_type == "uniform":
            self._set(arr, rng.uniform(-scale, scale, shape))
        else:
            self._set(arr, rng.normal(0.0, scale, shape))


@register("msraprelu")
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register("bilinear")
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i / shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register("lstmbias")
class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        self._set(arr, b)

    _init_bias = _init_weight


class Mixed:
    def __init__(self, patterns, initializers):
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(f"parameter {name} did not match any pattern")


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if isinstance(name, str) and name.startswith("["):
        import json
        kind, kw = json.loads(name)
        return _REG.get(kind)(**kw)
    if not isinstance(name, str) and callable(name):
        return name  # custom initializer object (e.g. Constant's closure)
    return _REG.get(name)(**kwargs)


# mx.init namespace alias
import sys as _sys
init = _sys.modules[__name__]
