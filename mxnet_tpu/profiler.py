"""Profiler (python/mxnet/profiler.py + src/profiler/ analog).

Keeps the reference's Python API (`set_config`, `set_state('run'/'stop')`,
`dump`, scopes/markers, aggregate per-op stats) while delegating the
device timeline to jax.profiler (XProf/TensorBoard traces) — the
SURVEY §5.1 plan. Op-level wall stats are collected at the dispatch
layer when profiling is on and dumped as Chrome trace-event JSON, same
consumption path (chrome://tracing) as the reference's profiler output.
"""
from __future__ import annotations

import json
import os
import threading
import time

import jax

from .telemetry.trace import current_trace_id as _current_trace_id

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause",
           "resume", "Task", "Frame", "Event", "Counter", "Marker",
           "profiler_set_config", "profiler_set_state", "Scope",
           "export_metrics"]

_CONFIG = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    "xprof_dir": None,
}
_STATE = {"running": False, "jax_trace": False}
_EVENTS: list = []
_AGGREGATE: dict = {}
_LOCK = threading.Lock()


def set_config(**kwargs):
    _CONFIG.update(kwargs)


profiler_set_config = set_config


def set_state(state_name="stop", profile_process="worker"):
    if state_name == "run":
        if _STATE["running"]:
            # idempotent: re-entering 'run' while running must neither
            # re-enter jax.profiler.start_trace (it raises on a second
            # start) nor clobber the session's peak_memory_bytes
            return
        _STATE["running"] = True
        _STATE.pop("peak_memory_bytes", None)  # fresh session, fresh peak
        if _STATE.get("jax_trace"):
            # 'run' after pause(): the device trace is still active —
            # re-entering start_trace would raise and orphan it
            return
        if os.environ.get("MXNET_PROFILER_AUTOSTART") != "0" and _CONFIG.get("xprof_dir"):
            try:
                jax.profiler.start_trace(_CONFIG["xprof_dir"])
                _STATE["jax_trace"] = True
            except Exception:
                _STATE["jax_trace"] = False
    elif state_name == "stop":
        if not _STATE["running"] and not _STATE.get("jax_trace"):
            return                             # idempotent no-op
        _STATE["running"] = False
        if _STATE.get("jax_trace"):
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            _STATE["jax_trace"] = False
    else:
        raise ValueError("state must be 'run' or 'stop'")


profiler_set_state = set_state


def state():
    return "run" if _STATE["running"] else "stop"


def peak_memory_bytes():
    """Peak device bytes_in_use observed across profiled ops (requires
    set_config(profile_memory=True) and a backend with memory stats;
    returns None if nothing was sampled)."""
    return _STATE.get("peak_memory_bytes")


def is_running():
    return _STATE["running"]


def _device_bytes_in_use():
    """Live device memory (reference src/profiler/ memory profiling
    analog): PJRT memory_stats when the backend provides them, else the
    byte total of live jax.Arrays (framework-tracked allocations — the
    runtime's pool internals aren't visible through the axon tunnel or
    the CPU backend)."""
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_in_use" in stats:
            return int(stats["bytes_in_use"])
    except Exception:
        pass
    try:
        return int(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:
        return None


def record_op(name, begin_us, end_us, category="operator", args=None):
    """Called from the dispatch layer (ThreadedEngine ProfileOperator
    analog). ``args`` lands in the Chrome-trace event's ``args`` dict —
    `Scope` stamps the active telemetry trace id through it so one
    request is findable in the device trace."""
    if not _STATE["running"]:
        return
    with _LOCK:
        ev = {"name": name, "cat": category, "ph": "X",
              "ts": begin_us, "dur": end_us - begin_us,
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = dict(args)
        if _CONFIG["profile_memory"]:
            mem = _device_bytes_in_use()
            if mem is not None:
                ev.setdefault("args", {})["bytes_in_use"] = mem
                peak = _STATE.get("peak_memory_bytes", 0)
                _STATE["peak_memory_bytes"] = max(peak, mem)
        _EVENTS.append(ev)
        if _CONFIG["aggregate_stats"]:
            agg = _AGGREGATE.setdefault(name, [0, 0.0, float("inf"), 0.0])
            dur = (end_us - begin_us) / 1e3
            agg[0] += 1
            agg[1] += dur
            agg[2] = min(agg[2], dur)
            agg[3] = max(agg[3], dur)


def dump(finished=True, profile_process="worker"):
    """Write Chrome trace-event JSON to the configured filename.

    The telemetry span ring (kept tail-sampled traces + in-flight
    spans) merges into the same stream — span and op events share one
    perf_counter microsecond axis, so chrome://tracing shows a slow
    request's queue/pack/forward spans next to the op timeline."""
    from .telemetry import spans as _spans
    span_events = _spans.export_chrome_events()
    with _LOCK:
        payload = {"traceEvents": list(_EVENTS) + span_events,
                   "displayTimeUnit": "ms"}
        with open(_CONFIG["filename"], "w") as f:
            json.dump(payload, f)
        if finished:
            _EVENTS.clear()


def dumps(reset=False, format="table"):
    """Aggregate per-op stats table (src/profiler/aggregate_stats.cc)."""
    with _LOCK:
        lines = [f"{'Name':<40}{'Count':>8}{'Total(ms)':>12}{'Min(ms)':>10}{'Max(ms)':>10}{'Avg(ms)':>10}"]
        for name, (cnt, tot, mn, mx) in sorted(_AGGREGATE.items(),
                                               key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<40}{cnt:>8}{tot:>12.3f}{mn:>10.3f}{mx:>10.3f}{tot / cnt:>10.3f}")
        if reset:
            _AGGREGATE.clear()
        return "\n".join(lines)


def export_metrics(registry=None):
    """Publish the aggregate per-op stats (``aggregate_stats=True``
    sessions) onto a telemetry registry as gauges —
    ``mxnet_tpu_profiler_op_calls{op=...}`` /
    ``..._op_total_ms{op=...}`` / ``..._op_max_ms{op=...}`` — so a
    /metrics scrape sees the same table ``dumps()`` prints. Returns
    the number of ops exported."""
    from .telemetry.registry import REGISTRY
    reg = registry if registry is not None else REGISTRY
    calls = reg.gauge("mxnet_tpu_profiler_op_calls",
                      "profiled calls per op", ("op",))
    total = reg.gauge("mxnet_tpu_profiler_op_total_ms",
                      "profiled wall ms per op", ("op",))
    mx_ms = reg.gauge("mxnet_tpu_profiler_op_max_ms",
                      "profiled max wall ms per op", ("op",))
    with _LOCK:
        agg = {name: tuple(v) for name, v in _AGGREGATE.items()}
    for name, (cnt, tot, _mn, mx) in agg.items():
        calls.labels(op=name).set(cnt)
        total.labels(op=name).set(round(tot, 3))
        mx_ms.labels(op=name).set(round(mx, 3))
    return len(agg)


def pause(profile_process="worker"):
    _STATE["running"] = False


def resume(profile_process="worker"):
    _STATE["running"] = True


class _Named:
    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"


class Task(_Named):
    def __init__(self, domain=None, name="task", args=None):
        super().__init__(name)
        self._start = None
        self._args = args

    def start(self):
        self._start = time.perf_counter_ns() // 1000

    def stop(self):
        if self._start is not None:
            record_op(self.name, self._start, time.perf_counter_ns() // 1000,
                      "task", args=self._args)
            self._start = None


class Frame(Task):
    pass


class Event(Task):
    pass


class Counter(_Named):
    def __init__(self, domain=None, name="counter", value=0):
        super().__init__(name)
        self.value = value

    def set_value(self, value):
        self.value = value

    def increment(self, delta=1):
        self.value += delta

    def decrement(self, delta=1):
        self.value -= delta


class Marker(_Named):
    def __init__(self, domain=None, name="marker"):
        super().__init__(name)

    def mark(self, scope="process"):
        now = time.perf_counter_ns() // 1000
        record_op(self.name, now, now, "marker")


class Scope:
    """with profiler.Scope('fwd'): ... — custom range.

    Stamps the active telemetry trace id (serving request ids minted at
    ``ServingEngine.submit``) into both the Chrome-trace event ``args``
    and the xprof TraceAnnotation metadata, so one request correlates
    across the wall-clock and device timelines. Degrades to
    wall-clock-only when ``jax.profiler.TraceAnnotation`` raises (a
    broken device-trace backend must not take the serving worker down,
    and the started wall-clock Task must still be closed)."""

    def __init__(self, name="scope"):
        self.name = name

    def __enter__(self):
        tid = _current_trace_id()
        self._t = Task(name=self.name,
                       args={"trace_id": tid} if tid else None)
        self._t.start()
        self._jax_ctx = None
        try:
            ctx = (jax.profiler.TraceAnnotation(self.name, trace_id=tid)
                   if tid else jax.profiler.TraceAnnotation(self.name))
            ctx.__enter__()
            self._jax_ctx = ctx
        except Exception:
            pass                      # wall-clock-only scope
        return self

    def __exit__(self, *exc):
        if self._jax_ctx is not None:
            try:
                self._jax_ctx.__exit__(*exc)
            except Exception:
                pass
        self._t.stop()
        return False
