"""``mx.npx`` — NumPy-extension namespace (operators NumPy itself lacks).

Analog of the reference's ``python/mxnet/numpy_extension/`` +
``mx.npx`` (v>=1.6): the np-mode switch (``set_np``/``reset_np``), the
neural-network operator surface under NumPy calling conventions
(relu/softmax/batch_norm/convolution/fully_connected/...), special
``reshape`` codes, and array save/load. Every op dispatches the same
registry kernels as the classic frontend — np-mode outputs are
``mx.np.ndarray`` via the dispatch-level wrap rule (see
ndarray/register.py invoke)."""
from __future__ import annotations

import functools

from ..util import is_np_array, is_np_shape, set_np, reset_np, use_np  # noqa: F401
from ..ndarray.register import get_op, invoke
from ..numpy.multiarray import ndarray, _np_invoke, _proc, asarray

__all__ = [
    "set_np", "reset_np", "is_np_array", "is_np_shape", "use_np",
    "relu", "sigmoid", "log_sigmoid", "softmax", "log_softmax", "softmin",
    "activation", "leaky_relu", "gelu", "erf", "erfinv", "gamma",
    "gammaln", "digamma", "batch_dot", "batch_norm", "layer_norm",
    "group_norm", "instance_norm", "l2_normalization", "fully_connected",
    "convolution", "deconvolution", "pooling", "dropout", "embedding",
    "one_hot", "pick", "topk", "rnn", "roi_pooling", "sequence_mask",
    "smooth_l1", "gather_nd", "scatter_nd", "arange_like",
    "broadcast_like", "reshape", "reshape_like", "ctc_loss",
    "multibox_prior", "multibox_target", "multibox_detection",
    "box_nms", "box_iou", "waitall", "save", "load", "seed",
]


def _ns(fname, opname, tensor_args=1):
    """Build an npx function dispatching a registry op: the leading
    ``tensor_args`` positionals are tensor inputs (None allowed for
    optional ones), the rest ride as params."""

    def f(*args, **kwargs):
        inputs = list(args[:tensor_args])
        extra = args[tensor_args:]
        if extra:
            raise TypeError(f"npx.{fname} takes at most {tensor_args} "
                            f"positional tensor arguments")
        inputs = [_proc(x) if x is not None else None for x in inputs]
        return _np_invoke(opname, inputs, kwargs or None)

    f.__name__ = fname
    f.__doc__ = f"npx.{fname}: numpy-mode dispatch of registry op {opname}."
    return f


# activations / math extensions
relu = _ns("relu", "relu")
sigmoid = _ns("sigmoid", "sigmoid")
log_sigmoid = _ns("log_sigmoid", "log_sigmoid")
softmax = _ns("softmax", "softmax")
log_softmax = _ns("log_softmax", "log_softmax")
softmin = _ns("softmin", "softmin")
activation = _ns("activation", "Activation")
leaky_relu = _ns("leaky_relu", "LeakyReLU")
gelu = _ns("gelu", "gelu")
erf = _ns("erf", "erf")
erfinv = _ns("erfinv", "erfinv")
gamma = _ns("gamma", "gamma")
gammaln = _ns("gammaln", "gammaln")
digamma = _ns("digamma", "digamma")
smooth_l1 = _ns("smooth_l1", "smooth_l1")

# contractions / nn layers (tensor arity follows the classic ops)
batch_dot = _ns("batch_dot", "batch_dot", tensor_args=2)
fully_connected = _ns("fully_connected", "FullyConnected", tensor_args=3)
convolution = _ns("convolution", "Convolution", tensor_args=3)
deconvolution = _ns("deconvolution", "Deconvolution", tensor_args=3)
pooling = _ns("pooling", "Pooling")
dropout = _ns("dropout", "Dropout")
embedding = _ns("embedding", "Embedding", tensor_args=2)
batch_norm = _ns("batch_norm", "BatchNorm", tensor_args=5)
layer_norm = _ns("layer_norm", "LayerNorm", tensor_args=3)
group_norm = _ns("group_norm", "GroupNorm", tensor_args=3)
instance_norm = _ns("instance_norm", "InstanceNorm", tensor_args=3)
l2_normalization = _ns("l2_normalization", "L2Normalization")
rnn = _ns("rnn", "RNN", tensor_args=4)
roi_pooling = _ns("roi_pooling", "ROIPooling", tensor_args=2)
ctc_loss = _ns("ctc_loss", "ctc_loss", tensor_args=4)

# indexing / shape extensions
one_hot = _ns("one_hot", "one_hot")
pick = _ns("pick", "pick", tensor_args=2)
topk = _ns("topk", "topk")
gather_nd = _ns("gather_nd", "gather_nd", tensor_args=2)
scatter_nd = _ns("scatter_nd", "scatter_nd", tensor_args=2)
arange_like = _ns("arange_like", "arange_like")
broadcast_like = _ns("broadcast_like", "broadcast_like", tensor_args=2)
sequence_mask = _ns("sequence_mask", "SequenceMask", tensor_args=2)
reshape_like = _ns("reshape_like", "reshape_like", tensor_args=2)


def reshape(a, newshape, reverse=False, order="C"):
    """npx.reshape with the classic special codes: 0 keep, -1 infer,
    -2 copy remainder, -3 merge next two, -4 split (takes two following
    values) — reference src/operator/tensor/matrix_op.cc semantics."""
    return _np_invoke("reshape", [_proc(a)],
                      {"shape": tuple(newshape), "reverse": reverse})


multibox_prior = _ns("multibox_prior", "_contrib_MultiBoxPrior")
multibox_target = _ns("multibox_target", "_contrib_MultiBoxTarget",
                      tensor_args=3)
multibox_detection = _ns("multibox_detection", "_contrib_MultiBoxDetection",
                         tensor_args=3)
box_nms = _ns("box_nms", "_contrib_box_nms")
box_iou = _ns("box_iou", "_contrib_box_iou", tensor_args=2)


def waitall():
    from ..engine import engine
    engine.wait_all()


def save(fname, data):
    """Save np arrays (dict/list/single) in the NDArray-file format."""
    from ..ndarray import serialization
    serialization.save(fname, data)


def load(fname):
    """Load arrays saved by :func:`save`, returned as np ndarrays."""
    from ..ndarray import serialization
    loaded = serialization.load(fname)
    if isinstance(loaded, dict):
        return {k: v.as_np_ndarray() for k, v in loaded.items()}
    if isinstance(loaded, list):
        return [v.as_np_ndarray() for v in loaded]
    return loaded.as_np_ndarray()


def seed(seed_state):
    from .. import random as _r
    _r.seed(seed_state)
