"""AttrScope — scoped symbol attributes (python/mxnet/attribute.py).

Used by the symbolic API to attach attributes (e.g. ``ctx_group`` for
manual model parallelism, ``__layout__``) to symbols created inside the
scope. On TPU, ctx_group placement maps to sharding annotations; the
scope mechanics are preserved for API parity.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]


class AttrScope:
    _current = threading.local()

    def __init__(self, **kwargs):
        self._attr = {str(k): str(v) for k, v in kwargs.items()}
        self._old = None

    def get(self, attr=None):
        merged = dict(getattr(AttrScope._current, "value", None)._attr
                      if getattr(AttrScope._current, "value", None) else {})
        if attr:
            merged.update(attr)
        return merged

    def __enter__(self):
        self._old = getattr(AttrScope._current, "value", None)
        if self._old is not None:
            merged = dict(self._old._attr)
            merged.update(self._attr)
            self._attr = merged
        AttrScope._current.value = self
        return self

    def __exit__(self, *exc):
        AttrScope._current.value = self._old
        return False

    @staticmethod
    def current() -> "AttrScope":
        cur = getattr(AttrScope._current, "value", None)
        if cur is None:
            cur = AttrScope()
            AttrScope._current.value = cur
        return cur


def current() -> AttrScope:
    return AttrScope.current()
