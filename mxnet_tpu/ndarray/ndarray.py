"""NDArray: the imperative tensor type.

Analog of the reference's ``include/mxnet/ndarray.h`` +
``src/ndarray/ndarray.cc`` + ``python/mxnet/ndarray/ndarray.py``. Design
per SURVEY §7: an NDArray wraps an immutable ``jax.Array`` plus a
version counter — the engine-variable analog. Mutation (in-place ops,
``x[...] = v``, ``out=`` kwargs, optimizer updates) rebinds ``_data`` to
a new buffer and bumps ``_version``; readers that captured the old
buffer (autograd tape residuals, views) keep a consistent snapshot by
construction, which is how the reference's versioned ThreadedVar
serializes writers against readers — here immutability gives it for
free.

Async semantics: every jax.Array is a future (PJRT async dispatch ≈
ThreadedEngine worker queues); ``wait_to_read`` = block_until_ready;
``asnumpy`` is the implicit sync point, exactly the reference contract
(src/c_api: MXNDArrayWaitToRead / MXNDArraySyncCopyToCPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, dtype_np, dtype_name
from ..context import Context, current_context
from ..engine import engine
from ..util import is_np_array as _is_np_array

__all__ = ["NDArray", "_wrap", "array", "empty", "zeros", "ones", "full", "arange"]


def _op(name):
    from .register import get_op
    return get_op(name)


def _invoke(name, inputs, params=None, out=None, ctx=None):
    from .register import invoke
    return invoke(_op(name), inputs, params, out=out, ctx=ctx)


class NDArray:
    """A multi-dimensional array with asynchronous execution and autograd.

    Not constructed directly by users — use ``mx.nd.array`` /
    ``mx.nd.zeros`` / op outputs (same as the reference, where NDArray
    handles come from the C API).
    """

    __slots__ = (
        "_data", "_ctx", "_version", "_grad", "_grad_req", "_is_leaf",
        "_in_graph", "_released", "__weakref__",
    )

    # numpy should defer binary-op dispatch to us
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Context | None = None):
        if ctx is None:
            ctx = current_context()
        self._data = data
        self._ctx = ctx
        self._version = 0
        self._grad = None
        self._grad_req = "null"
        self._is_leaf = False
        self._in_graph = False
        self._released = False

    # ------------------------------------------------------------------
    # internal plumbing
    # ------------------------------------------------------------------
    def _set_data(self, arr):
        """Rebind the backing buffer (a write: version bump)."""
        if arr.dtype != self._data.dtype:
            arr = arr.astype(self._data.dtype)
        if arr.shape != self._data.shape:
            raise MXNetError(
                f"in-place write shape mismatch: {arr.shape} vs {self._data.shape}")
        self._data = arr
        self._version += 1

    def _requires_grad_somewhere(self):
        return (self._is_leaf and self._grad_req != "null") or self._in_graph

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return self.transpose()

    @property
    def handle(self):  # legacy compat: opaque identity
        return id(self)

    # ------------------------------------------------------------------
    # sync / host transfer
    # ------------------------------------------------------------------
    def wait_to_read(self):
        engine.wait_for_var(self._data)

    def asnumpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        return f"\n{self.asnumpy()}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    # ------------------------------------------------------------------
    # copies / context movement
    # ------------------------------------------------------------------
    def copy(self) -> "NDArray":
        return _wrap(self._data + 0, self._ctx, cls=_wrap_cls_of(self))

    def copyto(self, other):
        """Copy to a Context or into another NDArray (CopyFromTo analog,
        src/ndarray/ndarray.cc)."""
        if isinstance(other, Context):
            arr = jax.device_put(self._data, other.jax_device)
            return _wrap(arr, other, cls=_wrap_cls_of(self))
        if isinstance(other, NDArray):
            if other is self:
                return other
            arr = jax.device_put(self._data, other._ctx.jax_device)
            if arr.dtype != other.dtype:
                arr = arr.astype(other.dtype)
            other._set_data(arr)
            return other
        raise MXNetError(f"cannot copyto {type(other)}")

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        """Classic-NDArray view of this array (zero-copy; the np
        subclass overrides the np side — python/mxnet/ndarray/ndarray.py
        as_np_ndarray/as_nd_ndarray interop contract)."""
        if type(self) is NDArray:
            return self
        return _convert_cls(self, NDArray)

    def as_np_ndarray(self):
        """mx.np.ndarray view of this array (zero-copy when not
        recording; routes through an identity op on the tape when
        recording so gradients flow across the conversion)."""
        if _NP_CLS is None or isinstance(self, _NP_CLS):
            return self
        return _convert_cls(self, _NP_CLS)

    def astype(self, dtype, copy=True) -> "NDArray":
        dt = dtype_np(dtype)
        if not copy and self.dtype == dt:
            return self
        return _wrap(self._data.astype(dt), self._ctx, cls=_wrap_cls_of(self))

    def cast(self, dtype):
        return self.astype(dtype)

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer; this array becomes a leaf."""
        from . import zeros
        g = zeros(self.shape, ctx=self._ctx, dtype=self.dtype)
        cls = _wrap_cls_of(self)
        if cls is not None:  # np arrays carry np gradients
            g = cls(g._data, g._ctx)
        self._grad = g
        self._grad_req = grad_req
        self._is_leaf = True

    @property
    def grad(self):
        return self._grad

    def detach(self) -> "NDArray":
        out = _wrap(self._data, self._ctx, cls=_wrap_cls_of(self))
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def zero_grad(self):
        if self._grad is not None:
            self._grad._set_data(jnp.zeros_like(self._grad._data))

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def _index_to_jax(self, key):
        def conv(k):
            if isinstance(k, NDArray):
                return k._data
            return k
        if isinstance(key, tuple):
            return tuple(conv(k) for k in key)
        return conv(key)

    def __getitem__(self, key):
        key = self._index_to_jax(key)
        return _invoke("_slice_get", [self], {"key": key})

    def __setitem__(self, key, value):
        key = self._index_to_jax(key)
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, np.ndarray):
            value = jnp.asarray(value)
        if hasattr(value, "dtype") and hasattr(value, "astype") and \
                value.dtype != self.dtype:
            value = value.astype(self.dtype)
        new = self._data.at[key].set(value)
        self._set_data(new)

    def slice(self, begin, end, step=None):
        return _invoke("slice", [self], {"begin": begin, "end": end, "step": step})

    def slice_axis(self, axis, begin, end):
        return _invoke("slice_axis", [self], {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return _invoke("take", [self, indices], {"axis": axis, "mode": mode})

    def pick(self, index, axis=-1, keepdims=False):
        return _invoke("pick", [self, index], {"axis": axis, "keepdims": keepdims})

    # ------------------------------------------------------------------
    # shape manipulation
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return _invoke("reshape", [self], {"shape": shape})

    def reshape_like(self, other):
        return _invoke("reshape_like", [self, other])

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _invoke("transpose", [self], {"axes": axes or None})

    def swapaxes(self, dim1, dim2):
        return _invoke("swapaxes", [self], {"dim1": dim1, "dim2": dim2})

    def flatten(self):
        return _invoke("Flatten", [self])

    def expand_dims(self, axis):
        return _invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return _invoke("squeeze", [self], {"axis": axis})

    def broadcast_to(self, shape):
        return _invoke("broadcast_to", [self], {"shape": shape})

    def broadcast_like(self, other):
        return _invoke("broadcast_like", [self, other])

    def tile(self, reps):
        return _invoke("tile", [self], {"reps": reps})

    def repeat(self, repeats, axis=None):
        return _invoke("repeat", [self], {"repeats": repeats, "axis": axis})

    def flip(self, axis):
        return _invoke("flip", [self], {"axis": axis})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return _invoke("split", [self], {"num_outputs": num_outputs, "axis": axis,
                                         "squeeze_axis": squeeze_axis})

    # ------------------------------------------------------------------
    # math methods (delegate to ops)
    # ------------------------------------------------------------------
    def sum(self, axis=None, keepdims=False, **kw):
        return _invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def nansum(self, axis=None, keepdims=False):
        return _invoke("nansum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False, **kw):
        return _invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return _invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return _invoke("min", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False):
        return _invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return _invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return _invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        return _invoke("argsort", [self], {"axis": axis, "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        return _invoke("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return _invoke("topk", [self], {"axis": axis, "k": k, "ret_typ": ret_typ,
                                        "is_ascend": is_ascend})

    def norm(self, ord=2, axis=None, keepdims=False):
        return _invoke("norm", [self], {"ord": ord, "axis": axis, "keepdims": keepdims})

    def abs(self):
        return _invoke("abs", [self])

    def exp(self):
        return _invoke("exp", [self])

    def log(self):
        return _invoke("log", [self])

    def sqrt(self):
        return _invoke("sqrt", [self])

    def square(self):
        return _invoke("square", [self])

    def sigmoid(self):
        return _invoke("sigmoid", [self])

    def relu(self):
        return _invoke("relu", [self])

    def softmax(self, axis=-1):
        return _invoke("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return _invoke("log_softmax", [self], {"axis": axis})

    def tanh(self):
        return _invoke("tanh", [self])

    def clip(self, a_min, a_max):
        return _invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def round(self):
        return _invoke("round", [self])

    def floor(self):
        return _invoke("floor", [self])

    def ceil(self):
        return _invoke("ceil", [self])

    def sign(self):
        return _invoke("sign", [self])

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32"):
        return _invoke("one_hot", [self], {"depth": depth, "on_value": on_value,
                                           "off_value": off_value, "dtype": dtype})

    def dot(self, other, transpose_a=False, transpose_b=False):
        return _invoke("dot", [self, other],
                       {"transpose_a": transpose_a, "transpose_b": transpose_b})

    def zeros_like(self):
        return _invoke("zeros_like", [self])

    def ones_like(self):
        return _invoke("ones_like", [self])

    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import cast_storage
        return cast_storage(self, stype)

    # ------------------------------------------------------------------
    # NumPy interop / pickling
    # ------------------------------------------------------------------
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __reduce__(self):
        # optimizer states & gluon params must pickle (kvstore server
        # updater round-trip in the reference pickles them too);
        # np ndarrays round-trip as np ndarrays
        is_np = _NP_CLS is not None and isinstance(self, _NP_CLS)
        return (_unpickle, (self.asnumpy(), dtype_name(self.dtype),
                            self._ctx.device_type, self._ctx.device_id,
                            is_np))


def _unpickle(npv, dtype, dev_type, dev_id, is_np=False):
    ctx = Context(dev_type, dev_id)
    out = array(npv, ctx=ctx, dtype=dtype)
    if is_np and _NP_CLS is not None:
        out = _NP_CLS(out._data, out._ctx)
    return out


def _binary_dunder(op_name, scalar_name=None, reverse=False):
    def fn(self, other):
        if isinstance(other, NDArray):
            return _invoke(op_name, [other, self] if reverse else [self, other])
        if isinstance(other, (np.ndarray, list, tuple)):
            other = array(other, ctx=self._ctx)
            return _invoke(op_name, [other, self] if reverse else [self, other])
        if isinstance(other, (int, float, bool, np.generic)):
            nm = scalar_name or (op_name + "_scalar")
            return _invoke(nm, [self], {"scalar": other, "reverse": reverse})
        return NotImplemented

    return fn


def _inplace_dunder(op_name):
    def fn(self, other):
        res = _binary_dunder(op_name)(self, other)
        if res is NotImplemented:
            return res
        self._set_data(res._data)
        return self

    return fn


# arithmetic
NDArray.__add__ = _binary_dunder("broadcast_add")
NDArray.__radd__ = _binary_dunder("broadcast_add", reverse=True)
NDArray.__sub__ = _binary_dunder("broadcast_sub")
NDArray.__rsub__ = _binary_dunder("broadcast_sub", reverse=True)
NDArray.__mul__ = _binary_dunder("broadcast_mul")
NDArray.__rmul__ = _binary_dunder("broadcast_mul", reverse=True)
NDArray.__truediv__ = _binary_dunder("broadcast_div")
NDArray.__rtruediv__ = _binary_dunder("broadcast_div", reverse=True)
NDArray.__mod__ = _binary_dunder("broadcast_mod")
NDArray.__rmod__ = _binary_dunder("broadcast_mod", reverse=True)
NDArray.__pow__ = _binary_dunder("broadcast_power")
NDArray.__rpow__ = _binary_dunder("broadcast_power", reverse=True)
NDArray.__matmul__ = lambda self, other: _invoke("matmul", [self, other])
NDArray.__iadd__ = _inplace_dunder("broadcast_add")
NDArray.__isub__ = _inplace_dunder("broadcast_sub")
NDArray.__imul__ = _inplace_dunder("broadcast_mul")
NDArray.__itruediv__ = _inplace_dunder("broadcast_div")
NDArray.__neg__ = lambda self: _invoke("negative", [self])
NDArray.__abs__ = lambda self: _invoke("abs", [self])
# comparisons
NDArray.__eq__ = _binary_dunder("broadcast_equal")
NDArray.__ne__ = _binary_dunder("broadcast_not_equal")
NDArray.__lt__ = _binary_dunder("broadcast_lesser")
NDArray.__le__ = _binary_dunder("broadcast_lesser_equal")
NDArray.__gt__ = _binary_dunder("broadcast_greater")
NDArray.__ge__ = _binary_dunder("broadcast_greater_equal")
NDArray.__hash__ = lambda self: id(self)


def _has(name):
    from .register import _OPS
    return name in _OPS


# installed by mxnet_tpu.numpy at import: the mx.np.ndarray subclass.
# invoke() wraps op outputs in this class when numpy semantics are
# active (mx.npx.set_np) or any input already is one — the analog of the
# reference routing np-mode handles to mxnet.numpy.ndarray
# (python/mxnet/numpy/multiarray.py).
_NP_CLS = None


def _wrap_cls_of(x):
    """Preserve the np-ndarray-ness of ``x`` across methods that wrap
    raw buffers directly (copy/astype/detach/...). Sparse subclasses
    keep their own overrides; everything non-np wraps as base NDArray."""
    if _NP_CLS is not None and isinstance(x, _NP_CLS):
        return _NP_CLS
    return None


def _convert_cls(x, cls):
    """Rewrap ``x`` as ``cls`` sharing the buffer; when autograd is
    recording, route through the identity op so the tape links the two
    objects (conversion must not silently detach the graph)."""
    from .. import autograd
    if autograd.is_recording() and x._requires_grad_somewhere():
        return _invoke_cls("_copy", [x], cls)
    return cls(x._data, x._ctx)


def _invoke_cls(name, inputs, cls):
    from .register import invoke
    return invoke(_op(name), inputs, wrap_cls=cls)


def _wrap(arr, ctx: Context | None = None, cls=None) -> NDArray:
    """Wrap a jax array (no copy) into an NDArray (or subclass). Under
    mx.npx.set_np the whole world is np-mode, so unclassed wraps
    (creation fns, loaders) come back as mx.np.ndarray too."""
    if ctx is None:
        ctx = current_context()
    if not isinstance(arr, (jnp.ndarray, jax.Array)):
        arr = jnp.asarray(arr)
    if cls is None and _NP_CLS is not None and _is_np_array():
        cls = _NP_CLS
    return (cls or NDArray)(arr, ctx)


# ----------------------------------------------------------------------
# creation functions (src/operator/tensor/init_op.cc analogs)
# ----------------------------------------------------------------------
def array(source, ctx: Context | None = None, dtype=None) -> NDArray:
    if ctx is None:
        ctx = current_context()
    if isinstance(source, NDArray):
        src = source._data
        dt = dtype_np(dtype) if dtype is not None else src.dtype
        return _wrap(jax.device_put(src.astype(dt), ctx.jax_device), ctx)
    npv = np.asarray(source)
    if dtype is None:
        # MXNet defaults python floats to float32 (not float64)
        dt = np.float32 if npv.dtype == np.float64 else npv.dtype
    else:
        dt = dtype_np(dtype)
    arr = jax.device_put(jnp.asarray(npv, dt), ctx.jax_device)
    return _wrap(arr, ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    if ctx is None:
        ctx = current_context()
    if isinstance(shape, int):
        shape = (shape,)
    with jax.default_device(ctx.jax_device):
        arr = jnp.zeros(shape, dtype_np(dtype))
    return _wrap(arr, ctx)


def ones(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    if ctx is None:
        ctx = current_context()
    if isinstance(shape, int):
        shape = (shape,)
    with jax.default_device(ctx.jax_device):
        arr = jnp.ones(shape, dtype_np(dtype))
    return _wrap(arr, ctx)


def full(shape, val, ctx=None, dtype=None) -> NDArray:
    if ctx is None:
        ctx = current_context()
    if isinstance(shape, int):
        shape = (shape,)
    with jax.default_device(ctx.jax_device):
        arr = jnp.full(shape, val, dtype_np(dtype))
    return _wrap(arr, ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    if ctx is None:
        ctx = current_context()
    with jax.default_device(ctx.jax_device):
        arr = jnp.arange(start, stop, step, dtype_np(dtype))
        if repeat > 1:
            arr = jnp.repeat(arr, repeat)
    return _wrap(arr, ctx)
