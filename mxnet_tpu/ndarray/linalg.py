"""mx.nd.linalg namespace (reference src/operator/tensor/la_op.cc)."""
from __future__ import annotations

from .register import invoke as _invoke, get_op as _get_op


def gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, axis=-2):
    return _invoke(_get_op("linalg_gemm"), [A, B, C],
                   {"transpose_a": transpose_a, "transpose_b": transpose_b,
                    "alpha": alpha, "beta": beta})


def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    return _invoke(_get_op("linalg_gemm2"), [A, B],
                   {"transpose_a": transpose_a, "transpose_b": transpose_b,
                    "alpha": alpha})


def potrf(A):
    return _invoke(_get_op("linalg_potrf"), [A])


def trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    return _invoke(_get_op("linalg_trsm"), [A, B],
                   {"transpose": transpose, "rightside": rightside,
                    "lower": lower, "alpha": alpha})


def sumlogdiag(A):
    return _invoke(_get_op("linalg_sumlogdiag"), [A])


def extractdiag(A, offset=0):
    return _invoke(_get_op("linalg_extractdiag"), [A], {"offset": offset})


def syrk(A, transpose=False, alpha=1.0):
    return _invoke(_get_op("linalg_syrk"), [A], {"transpose": transpose, "alpha": alpha})
