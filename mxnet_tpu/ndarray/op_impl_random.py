"""Random sampling operators.

Analog of the reference's ``src/operator/random/sample_op.{cc,cu}``
(uniform/normal/gamma/exponential/poisson/negative_binomial/
generalized_negative_binomial/randint), ``multinomial``/``sample_*``
distribution ops and ``shuffle``. The per-device curand/Philox resource
(src/common/random_generator.h) maps to the threefry key chain in
mxnet_tpu/random.py — functional splitting instead of stateful streams,
which is what makes these ops safe under XLA tracing.

All sampling ops are non-differentiable (matches reference: no
FGradient on sample ops).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import dtype_np
from .register import register_op
from .. import random as _random


def _key(k):
    return _random._next_key() if k is None else k


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


@register_op("random_uniform", aliases=("_random_uniform", "uniform"),
             differentiable=False)
def random_uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None,
                   _rng_key=None):
    dt = dtype_np(dtype)
    return jax.random.uniform(_key(_rng_key), _shape(shape), dt, low, high)


@register_op("random_normal", aliases=("_random_normal", "normal"),
             differentiable=False)
def random_normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None,
                  _rng_key=None):
    dt = dtype_np(dtype)
    return loc + scale * jax.random.normal(_key(_rng_key), _shape(shape), dt)


@register_op("random_gamma", aliases=("_random_gamma",), differentiable=False)
def random_gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None,
                 _rng_key=None):
    dt = dtype_np(dtype)
    return beta * jax.random.gamma(_key(_rng_key), alpha, _shape(shape), dt)


@register_op("random_exponential", aliases=("_random_exponential", "exponential"),
             differentiable=False)
def random_exponential(lam=1.0, shape=None, dtype="float32", ctx=None, _rng_key=None):
    dt = dtype_np(dtype)
    return jax.random.exponential(_key(_rng_key), _shape(shape), dt) / lam


@register_op("random_poisson", aliases=("_random_poisson", "poisson"),
             differentiable=False)
def random_poisson(lam=1.0, shape=None, dtype="float32", ctx=None, _rng_key=None):
    return jax.random.poisson(_key(_rng_key), lam, _shape(shape)).astype(dtype_np(dtype))


@register_op("random_negative_binomial", aliases=("_random_negative_binomial",),
             differentiable=False)
def random_negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None,
                             _rng_key=None):
    key1, key2 = jax.random.split(_key(_rng_key))
    lam = jax.random.gamma(key1, k, _shape(shape)) * ((1 - p) / p)
    return jax.random.poisson(key2, lam, _shape(shape)).astype(dtype_np(dtype))


@register_op("random_randint", aliases=("_random_randint", "randint"),
             differentiable=False)
def random_randint(low=0, high=100, shape=None, dtype="int32", ctx=None,
                   _rng_key=None):
    return jax.random.randint(_key(_rng_key), _shape(shape), int(low), int(high),
                              dtype_np(dtype))


@register_op("sample_uniform", differentiable=False)
def sample_uniform(low, high, shape=None, dtype=None, _rng_key=None):
    s = _shape(shape)
    out_shape = low.shape + s
    u = jax.random.uniform(_key(_rng_key), out_shape, low.dtype)
    low_b = low.reshape(low.shape + (1,) * len(s))
    high_b = high.reshape(high.shape + (1,) * len(s))
    return low_b + u * (high_b - low_b)


@register_op("sample_normal", differentiable=False)
def sample_normal(mu, sigma, shape=None, dtype=None, _rng_key=None):
    s = _shape(shape)
    out_shape = mu.shape + s
    z = jax.random.normal(_key(_rng_key), out_shape, mu.dtype)
    return mu.reshape(mu.shape + (1,) * len(s)) + z * sigma.reshape(sigma.shape + (1,) * len(s))


@register_op("sample_gamma", differentiable=False)
def sample_gamma(alpha, beta, shape=None, dtype=None, _rng_key=None):
    s = _shape(shape)
    a = alpha.reshape(alpha.shape + (1,) * len(s))
    g = jax.random.gamma(_key(_rng_key), jnp.broadcast_to(a, alpha.shape + s))
    return g * beta.reshape(beta.shape + (1,) * len(s))


@register_op("sample_multinomial", aliases=("_sample_multinomial", "multinomial"),
             differentiable=False)
def sample_multinomial(data, shape=None, get_prob=False, dtype="int32", _rng_key=None):
    s = _shape(shape)
    n = int(np.prod(s)) if s else 1
    logits = jnp.log(jnp.maximum(data, 1e-37))
    idx = jax.random.categorical(_key(_rng_key), logits, axis=-1,
                                 shape=(n,) + data.shape[:-1])
    if data.ndim == 1:
        out = idx.reshape(s) if s else idx.reshape(())
    else:
        out = jnp.moveaxis(idx, 0, -1).reshape(data.shape[:-1] + (s if s else ()))
    out = out.astype(dtype_np(dtype))
    if get_prob:
        logp = jnp.log(jnp.maximum(data, 1e-37))
        p = jnp.take_along_axis(
            jnp.broadcast_to(logp, out.shape + (data.shape[-1],)),
            out.astype(jnp.int32)[..., None], axis=-1).squeeze(-1)
        return out, p
    return out


@register_op("shuffle", aliases=("_shuffle",), differentiable=False)
def shuffle(data, _rng_key=None):
    return jax.random.permutation(_key(_rng_key), data, axis=0)


@register_op("bernoulli", aliases=("_sample_bernoulli",), differentiable=False)
def bernoulli(prob=None, logit=None, shape=None, dtype="float32", _rng_key=None):
    if prob is None and logit is not None:
        prob = jax.nn.sigmoid(logit)
    s = _shape(shape) or (prob.shape if hasattr(prob, "shape") else ())
    return jax.random.bernoulli(_key(_rng_key), prob, s or None).astype(dtype_np(dtype))
