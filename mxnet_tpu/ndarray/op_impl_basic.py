"""Tensor operators: elementwise, broadcast, reductions, shape/index ops.

TPU-native implementations of the reference's ``src/operator/tensor/``
family (elemwise_unary_op_basic.cc, elemwise_binary_op_basic.cc,
broadcast_reduce_op_value.cc, matrix_op.cc, indexing_op.cc,
ordering_op.cc, init_op.cc) and the mshadow functor library
(src/operator/mshadow_op.h). Each op is a pure jax function registered
through the op registry; XLA fuses elementwise chains (the mshadow
Kernel::Launch analog is simply XLA fusion) and tiles matmuls onto the
MXU. Gradients come from jax.vjp — the per-op FGradient table of the
reference collapses into JAX's AD rules.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import dtype_np
from .register import register_op

# ----------------------------------------------------------------------
# elementwise unary (mshadow_op.h functors)
# ----------------------------------------------------------------------
_UNARY = {
    "abs": jnp.abs,
    "exp": jnp.exp,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "square": jnp.square,
    "reciprocal": lambda x: 1.0 / x,
    "negative": jnp.negative,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "gamma": lambda x: jnp.exp(jax.lax.lgamma(x)),
    "gammaln": lambda x: jax.lax.lgamma(x),
    "erf": jax.lax.erf,
    "erfinv": jax.lax.erf_inv,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "identity": lambda x: x + 0,
}

for _name, _fn in _UNARY.items():
    register_op(_name)(_fn)

register_op("copy", aliases=("_copy",))(lambda x: x + 0)
register_op("BlockGrad", aliases=("stop_gradient",), differentiable=False)(
    lambda x: lax.stop_gradient(x))
register_op("make_loss")(lambda x: x + 0)

_NONDIFF_UNARY = {
    "round": jnp.round,
    "rint": jnp.rint,
    "fix": jnp.trunc,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "trunc": jnp.trunc,
    "sign": jnp.sign,
    "logical_not": lambda x: jnp.logical_not(x.astype(bool)).astype(x.dtype),
    "isnan": lambda x: jnp.isnan(x),
    "isinf": lambda x: jnp.isinf(x),
    "isfinite": lambda x: jnp.isfinite(x),
}
for _name, _fn in _NONDIFF_UNARY.items():
    register_op(_name, differentiable=False)(_fn)


@register_op("clip")
def clip(x, a_min=None, a_max=None):
    return jnp.clip(x, a_min, a_max)


@register_op("Cast", aliases=("cast",))
def cast(x, dtype="float32"):
    return x.astype(dtype_np(dtype))


@register_op("_constant", differentiable=False)
def _constant(value=None, dtype="float32"):
    """Embed a small static constant into the graph (works on every
    frontend path: eager, traced, and SYMBOLIC — symbols cannot wrap
    runtime numpy arrays, so constants must be op parameters)."""
    return jnp.asarray(np.asarray(value), dtype_np(dtype))


@register_op("amp_cast")
def amp_cast(x, dtype="float32"):
    return x.astype(dtype_np(dtype))


@register_op("amp_multicast", wrap=False, dynamic_arity=True)
def amp_multicast(*xs, num_outputs=None, cast_narrow=False):
    dts = [x.dtype for x in xs]
    widths = [jnp.dtype(d).itemsize for d in dts]
    target = dts[int(np.argmin(widths))] if cast_narrow else dts[int(np.argmax(widths))]
    return tuple(x.astype(target) for x in xs)


# ----------------------------------------------------------------------
# broadcast binary (elemwise_binary_op_basic.cc + broadcast_op)
# jnp broadcasting covers both the reference's elemwise_* (same-shape)
# and broadcast_* (numpy rules) variants.
# ----------------------------------------------------------------------
_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
}
_BIN_ALIASES = {
    "broadcast_add": ("elemwise_add", "_plus", "_add"),
    "broadcast_sub": ("elemwise_sub", "_minus", "_sub"),
    "broadcast_mul": ("elemwise_mul", "_mul"),
    "broadcast_div": ("elemwise_div", "_div"),
    "broadcast_power": ("_power",),
    "broadcast_mod": ("_mod",),
}
for _name, _fn in _BINARY.items():
    register_op(_name, aliases=_BIN_ALIASES.get(_name, ()))(_fn)

_CMP = {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": lambda a, b: jnp.logical_and(a, b),
    "broadcast_logical_or": lambda a, b: jnp.logical_or(a, b),
    "broadcast_logical_xor": lambda a, b: jnp.logical_xor(a, b),
}


def _cmp_wrap(fn):
    # MXNet comparison ops return the input dtype (1.0/0.0), not bool
    def impl(lhs, rhs):
        dt = lhs.dtype if hasattr(lhs, "dtype") else jnp.float32
        return fn(lhs, rhs).astype(dt)
    return impl


for _name, _fn in _CMP.items():
    register_op(_name, differentiable=False)(_cmp_wrap(_fn))


# scalar variants (mshadow_op scalar kernels; _plus_scalar etc.)
def _scalar_op(fn, swap_ok=True):
    def impl(x, scalar=0.0, reverse=False):
        a, b = (scalar, x) if reverse else (x, scalar)
        out = fn(a, b)
        dt = x.dtype
        if out.dtype != dt and jnp.issubdtype(dt, jnp.floating):
            out = out.astype(dt)
        return out
    return impl


_SCALAR = {
    "broadcast_add_scalar": (jnp.add, ("_plus_scalar",)),
    "broadcast_sub_scalar": (jnp.subtract, ("_minus_scalar",)),
    "broadcast_mul_scalar": (jnp.multiply, ("_mul_scalar",)),
    "broadcast_div_scalar": (jnp.divide, ("_div_scalar",)),
    "broadcast_mod_scalar": (jnp.mod, ("_mod_scalar",)),
    "broadcast_power_scalar": (jnp.power, ("_power_scalar",)),
    "broadcast_maximum_scalar": (jnp.maximum, ("_maximum_scalar",)),
    "broadcast_minimum_scalar": (jnp.minimum, ("_minimum_scalar",)),
}
for _name, (_fn, _al) in _SCALAR.items():
    register_op(_name, aliases=_al)(_scalar_op(_fn))


# reversed-scalar ops (MXNet contract: scalar ∘ tensor)
def _rev_scalar_op(fn):
    def impl(x, scalar=0.0, reverse=True):
        out = fn(scalar, x)
        if out.dtype != x.dtype and jnp.issubdtype(x.dtype, jnp.floating):
            out = out.astype(x.dtype)
        return out
    return impl


register_op("_rminus_scalar")(_rev_scalar_op(jnp.subtract))
register_op("_rdiv_scalar")(_rev_scalar_op(jnp.divide))
register_op("_rpower_scalar")(_rev_scalar_op(jnp.power))
register_op("_rmod_scalar")(_rev_scalar_op(jnp.mod))

_SCALAR_CMP = {
    "broadcast_equal_scalar": jnp.equal,
    "broadcast_not_equal_scalar": jnp.not_equal,
    "broadcast_greater_scalar": jnp.greater,
    "broadcast_greater_equal_scalar": jnp.greater_equal,
    "broadcast_lesser_scalar": jnp.less,
    "broadcast_lesser_equal_scalar": jnp.less_equal,
}
for _name, _fn in _SCALAR_CMP.items():
    def _mk(fn):
        def impl(x, scalar=0.0, reverse=False):
            a, b = (scalar, x) if reverse else (x, scalar)
            return fn(a, b).astype(x.dtype)
        return impl
    register_op(_name, differentiable=False)(_mk(_fn))


@register_op("add_n", aliases=("ElementWiseSum", "_sum"))
def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register_op("where")
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register_op("maximum")
def maximum(lhs, rhs):
    return jnp.maximum(lhs, rhs)


@register_op("minimum")
def minimum(lhs, rhs):
    return jnp.minimum(lhs, rhs)


# ----------------------------------------------------------------------
# reductions (broadcast_reduce_op_value.cc)
# ----------------------------------------------------------------------
def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return int(axis)


def _reduce(fn):
    def impl(x, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            all_ax = set(range(x.ndim))
            keep = {a % x.ndim for a in (ax if isinstance(ax, tuple) else (ax,))}
            ax = tuple(sorted(all_ax - keep))
        return fn(x, axis=ax, keepdims=bool(keepdims))
    return impl


register_op("sum", aliases=("sum_axis",))(_reduce(jnp.sum))
register_op("nansum")(_reduce(jnp.nansum))
register_op("mean")(_reduce(jnp.mean))
register_op("prod")(_reduce(jnp.prod))
register_op("nanprod")(_reduce(jnp.nanprod))
register_op("max", aliases=("max_axis",))(_reduce(jnp.max))
register_op("min", aliases=("min_axis",))(_reduce(jnp.min))


@register_op("norm")
def norm(x, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=bool(keepdims)))


@register_op("L2Normalization")
def l2_normalization(x, eps=1e-10, mode="instance"):
    if mode == "instance":
        ax = tuple(range(1, x.ndim))
    elif mode == "channel":
        ax = 1
    else:  # spatial
        ax = tuple(range(2, x.ndim))
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=True) + eps)
    return x / n


def _argreduce(fn):
    def impl(x, axis=None, keepdims=False):
        ax = axis
        if ax is None:
            out = fn(x.reshape(-1), axis=0)
            return out.astype(jnp.float32)
        out = fn(x, axis=int(ax))
        if keepdims:
            out = jnp.expand_dims(out, int(ax))
        return out.astype(jnp.float32)
    return impl


register_op("argmax", differentiable=False)(_argreduce(jnp.argmax))
register_op("argmin", differentiable=False)(_argreduce(jnp.argmin))


@register_op("argmax_channel", differentiable=False)
def argmax_channel(x):
    return jnp.argmax(x, axis=1).astype(jnp.float32)


# ----------------------------------------------------------------------
# shape ops (matrix_op.cc)
# ----------------------------------------------------------------------
@register_op("reshape", aliases=("Reshape",))
def reshape(x, shape=None, reverse=False):
    """MXNet reshape with special codes 0 (keep), -1 (infer), -2 (copy
    rest), -3 (merge next two), -4 (split, takes two following values)."""
    shape = tuple(shape)
    if not any(s in (0, -2, -3, -4) for s in shape):
        return jnp.reshape(x, shape)
    src = list(x.shape)
    if reverse:
        src = src[::-1]
        shape = tuple(reversed(shape))
    out = []
    i = 0  # index into src
    j = 0
    shape = list(shape)
    while j < len(shape):
        s = shape[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            d1, d2 = shape[j + 1], shape[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(s); i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return jnp.reshape(x, tuple(out))


@register_op("reshape_like")
def reshape_like(x, other):
    return jnp.reshape(x, other.shape)


@register_op("shape_array", differentiable=False)
def shape_array(x):
    return jnp.asarray(x.shape, dtype=jnp.int64)


@register_op("size_array", differentiable=False)
def size_array(x):
    return jnp.asarray([x.size], dtype=jnp.int64)


@register_op("transpose")
def transpose(x, axes=None):
    return jnp.transpose(x, axes)


@register_op("swapaxes", aliases=("SwapAxis",))
def swapaxes(x, dim1=0, dim2=1):
    return jnp.swapaxes(x, int(dim1), int(dim2))


@register_op("Flatten", aliases=("flatten",))
def flatten(x):
    return jnp.reshape(x, (x.shape[0], -1))


@register_op("expand_dims")
def expand_dims(x, axis=0):
    return jnp.expand_dims(x, int(axis))


@register_op("squeeze")
def squeeze(x, axis=None):
    return jnp.squeeze(x, axis if axis is None else tuple(np.atleast_1d(axis)))


@register_op("broadcast_to")
def broadcast_to(x, shape=None):
    shape = tuple(int(t) if t != 0 else s for t, s in zip(shape, x.shape))
    return jnp.broadcast_to(x, shape)


@register_op("broadcast_like")
def broadcast_like(x, other):
    return jnp.broadcast_to(x, other.shape)


@register_op("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(x, axis=(), size=()):
    axis = tuple(np.atleast_1d(axis))
    size = tuple(np.atleast_1d(size))
    target = list(x.shape)
    for a, s in zip(axis, size):
        target[a] = int(s)
    return jnp.broadcast_to(x, tuple(target))


@register_op("tile")
def tile(x, reps=()):
    return jnp.tile(x, tuple(reps))


@register_op("repeat")
def repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, int(repeats), axis=None if axis is None else int(axis))


@register_op("flip", aliases=("reverse",))
def flip(x, axis=0):
    return jnp.flip(x, tuple(np.atleast_1d(axis)))


@register_op("pad", aliases=("Pad",))
def pad(x, mode="constant", pad_width=(), constant_value=0.0):
    pw = tuple(pad_width)
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pairs, mode="constant", constant_values=constant_value)
    return jnp.pad(x, pairs, mode=jmode)


@register_op("depth_to_space")
def depth_to_space(x, block_size=1):
    b = int(block_size)
    n, c, h, w = x.shape
    y = x.reshape(n, b, b, c // (b * b), h, w)
    y = y.transpose(0, 3, 4, 1, 5, 2)
    return y.reshape(n, c // (b * b), h * b, w * b)


@register_op("space_to_depth")
def space_to_depth(x, block_size=1):
    b = int(block_size)
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // b, b, w // b, b)
    y = y.transpose(0, 3, 5, 1, 2, 4)
    return y.reshape(n, c * b * b, h // b, w // b)


# ----------------------------------------------------------------------
# slicing & indexing (matrix_op.cc / indexing_op.cc)
# ----------------------------------------------------------------------
@register_op("_slice_get", wrap=False)
def _slice_get(x, key=None):
    return x[key]


@register_op("slice", aliases=("crop",))
def slice_op(x, begin=(), end=(), step=None):
    idx = []
    step = step or [None] * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(builtins_slice(b, e, s))
    return x[tuple(idx)]


def builtins_slice(b, e, s):
    return slice(b, e, s)


@register_op("slice_axis")
def slice_axis(x, axis=0, begin=0, end=None):
    axis = int(axis) % x.ndim
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register_op("slice_like")
def slice_like(x, shape_like, axes=()):
    axes = tuple(np.atleast_1d(axes)) if axes != () and axes is not None else tuple(range(x.ndim))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, shape_like.shape[a])
    return x[tuple(idx)]


@register_op("take")
def take(x, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    jmode = "clip" if mode == "clip" else "wrap"
    return jnp.take(x, idx, axis=int(axis), mode=jmode)


@register_op("batch_take")
def batch_take(x, indices):
    idx = indices.astype(jnp.int32).reshape(-1)
    return x[jnp.arange(x.shape[0]), idx]


@register_op("pick")
def pick(x, index, axis=-1, keepdims=False, mode="clip"):
    ax = int(axis) % x.ndim
    idx = jnp.clip(index.astype(jnp.int32), 0, x.shape[ax] - 1)
    picked = jnp.take_along_axis(x, jnp.expand_dims(idx, ax), axis=ax)
    if not keepdims:
        picked = jnp.squeeze(picked, ax)
    return picked


@register_op("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register_op("scatter_nd", wrap=False)
def scatter_nd(data, indices, shape=None):
    idx = tuple(indices.astype(jnp.int32))
    out = jnp.zeros(tuple(shape), data.dtype)
    return out.at[idx].add(data)


@register_op("one_hot", differentiable=False)
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), int(depth), dtype=dtype_np(dtype))
    return oh * (on_value - off_value) + off_value


@register_op("SequenceMask")
def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data + 0
    ax = int(axis)
    T = data.shape[ax]
    steps = jnp.arange(T)
    shape = [1] * data.ndim
    shape[ax] = T
    steps = steps.reshape(shape)
    batch_axis = 1 if ax == 0 else 0
    lshape = [1] * data.ndim
    lshape[batch_axis] = data.shape[batch_axis]
    lens = sequence_length.reshape(lshape)
    return jnp.where(steps < lens, data, jnp.asarray(value, data.dtype))


@register_op("SequenceLast")
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    ax = int(axis)
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[ax] = -1
        return data[tuple(idx)]
    last = sequence_length.astype(jnp.int32) - 1  # shape (batch,)
    batch_axis = 1 if ax == 0 else 0
    shape = [1] * data.ndim
    shape[batch_axis] = data.shape[batch_axis]
    idx = jnp.broadcast_to(
        last.reshape(shape),
        tuple(1 if i == ax else data.shape[i] for i in range(data.ndim)))
    return jnp.take_along_axis(data, idx, axis=ax).squeeze(ax)


@register_op("SequenceReverse")
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, int(axis))
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    lens = sequence_length.astype(jnp.int32)[None, :]
    rev_idx = jnp.where(steps < lens, lens - 1 - steps, steps)
    return jnp.take_along_axis(
        data, rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2)), axis=0)


# ----------------------------------------------------------------------
# concat / stack / split
# ----------------------------------------------------------------------
@register_op("concat", aliases=("Concat",))
def concat(*args, dim=1, num_args=None):
    return jnp.concatenate(args, axis=int(dim))


@register_op("stack")
def stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=int(axis))


@register_op("split", aliases=("SliceChannel",), wrap=False,
             dynamic_arity=True)
def split(x, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(x, int(num_outputs), axis=int(axis))
    if squeeze_axis:
        parts = [jnp.squeeze(p, int(axis)) for p in parts]
    return tuple(parts)


@register_op("split_v2", wrap=False)
def split_v2(x, indices_or_sections=1, axis=0, squeeze_axis=False):
    if isinstance(indices_or_sections, int):
        parts = jnp.split(x, indices_or_sections, axis=int(axis))
    else:
        parts = jnp.split(x, list(indices_or_sections), axis=int(axis))
    if squeeze_axis:
        parts = [jnp.squeeze(p, int(axis)) for p in parts]
    return tuple(parts)


# ----------------------------------------------------------------------
# dot / batch_dot / matmul (dot-inl.h — MXU territory)
# ----------------------------------------------------------------------
@register_op("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False, forward_stype=None):
    a = lhs.T if transpose_a and lhs.ndim == 2 else (jnp.moveaxis(lhs, 0, -1) if transpose_a else lhs)
    b = rhs.T if transpose_b and rhs.ndim == 2 else (jnp.moveaxis(rhs, -1, 0) if transpose_b else rhs)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # MXNet dot: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register_op("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False, forward_stype=None):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register_op("matmul", aliases=("linalg_gemm2_nn",))
def matmul(a, b):
    return jnp.matmul(a, b)


@register_op("khatri_rao")
def khatri_rao(*args):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(-1, out.shape[-1])
    return out


# ----------------------------------------------------------------------
# ordering (ordering_op.cc)
# ----------------------------------------------------------------------
@register_op("sort")
def sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=None if axis is None else int(axis))
    if not is_ascend:
        out = jnp.flip(out, axis=-1 if axis is None else int(axis))
    return out


@register_op("argsort", differentiable=False)
def argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    key = x if is_ascend else -x
    out = jnp.argsort(key, axis=None if axis is None else int(axis))
    return out.astype(dtype_np(dtype))


@register_op("topk", differentiable=False, wrap=False)
def topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    ax = x.ndim - 1 if axis is None else int(axis) % x.ndim
    xs = jnp.moveaxis(x, ax, -1)
    vals, idx = jax.lax.top_k(xs if not is_ascend else -xs, int(k))
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax)
    if ret_typ == "indices":
        return idx.astype(dtype_np(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "mask":
        oh = jnp.sum(jax.nn.one_hot(jnp.moveaxis(idx, ax, -1), x.shape[ax], dtype=x.dtype), axis=-2)
        return jnp.moveaxis(oh, -1, ax)
    return (vals, idx.astype(dtype_np(dtype)))  # 'both'


# ----------------------------------------------------------------------
# init-like ops
# ----------------------------------------------------------------------
@register_op("zeros_like")
def zeros_like(x):
    return jnp.zeros_like(x)


@register_op("ones_like")
def ones_like(x):
    return jnp.ones_like(x)


@register_op("_full_like", wrap=False)
def full_like(x, value=0.0):
    return jnp.full_like(x, value)


@register_op("_arange_like", aliases=("arange_like",), differentiable=False)
def arange_like(x, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = x.size
    else:
        n = x.shape[int(axis)]
    return jnp.arange(start, start + step * n, step, dtype=x.dtype)


# ----------------------------------------------------------------------
# linalg (la_op.cc subset)
# ----------------------------------------------------------------------
@register_op("linalg_gemm")
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register_op("linalg_gemm2")
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register_op("linalg_potrf")
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register_op("linalg_trsm")
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    if rightside:
        x = jnp.swapaxes(jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * B, -1, -2),
            lower=not lower if transpose else lower), -1, -2)
    else:
        x = jax.scipy.linalg.solve_triangular(
            a, alpha * B, lower=not lower if transpose else lower)
    return x


@register_op("linalg_sumlogdiag")
def linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register_op("linalg_extractdiag")
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=int(offset), axis1=-2, axis2=-1)


@register_op("linalg_syrk")
def linalg_syrk(A, transpose=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))


@register_op("linalg_potri")
def linalg_potri(A):
    """Inverse of B = A A^T from its Cholesky factor A (la_op.cc potri):
    (A A^T)^{-1} = A^{-T} A^{-1}, via two triangular solves — no
    general inverse materializes."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    ainv = jax.scipy.linalg.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(ainv, -1, -2), ainv)


@register_op("linalg_trmm")
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular matrix multiply (la_op.cc trmm): B <- alpha op(tri(A))
    B, or B op(tri(A)) when rightside."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    out = jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B)
    return alpha * out


@register_op("linalg_makediag")
def linalg_makediag(A, offset=0):
    """(..., n) vector(s) -> (..., n+|k|, n+|k|) diagonal matrices."""
    offset = int(offset)
    n = A.shape[-1] + abs(offset)
    rows, cols = np.nonzero(np.eye(n, k=offset, dtype=bool))
    out = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    return out.at[..., rows, cols].set(A)


def _tri_count(n, offset, lower):
    """Entries in the (lower, k=offset) / (upper, k=offset) triangle of
    an (n, n) matrix — closed form, no index materialization."""
    k = offset if lower else -offset  # upper(k) == lower(-k) transposed
    # lower triangle with diagonal shift k: rows i get
    # clip(i + k + 1, 0, n) entries
    c = np.clip(np.arange(n) + k + 1, 0, n)
    return int(c.sum())


def _trian_n(m, offset, lower):
    """Matrix size n whose triangle has m entries (closed-form count,
    linear scan over n without building index arrays)."""
    for n in range(1, 65536):
        cnt = _tri_count(n, offset, lower)
        if cnt == m:
            return n
        if cnt > m:
            break
    raise ValueError(f"no matrix size has a {m}-entry triangle "
                     f"(offset={offset}, lower={lower})")


@register_op("linalg_maketrian")
def linalg_maketrian(A, offset=0, lower=True):
    """Packed (..., m) vector -> (..., n, n) triangular matrix, row-major
    packing (la_op.cc maketrian)."""
    offset, lower = int(offset), bool(lower)
    n = _trian_n(A.shape[-1], offset, lower)
    rows, cols = (np.tril_indices(n, k=offset) if lower
                  else np.triu_indices(n, k=offset))
    out = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    return out.at[..., rows, cols].set(A)


@register_op("linalg_extracttrian")
def linalg_extracttrian(A, offset=0, lower=True):
    """(..., n, n) -> packed (..., m) triangle, row-major (inverse of
    maketrian)."""
    offset, lower = int(offset), bool(lower)
    n = A.shape[-1]
    rows, cols = (np.tril_indices(n, k=offset) if lower
                  else np.triu_indices(n, k=offset))
    return A[..., rows, cols]


# ----------------------------------------------------------------------
# im2col / col2im (src/operator/nn/im2col.h surface ops)
# ----------------------------------------------------------------------
def _conv_geom(kernel, stride, dilate, pad):
    k = tuple(int(v) for v in kernel)
    nd_ = len(k)
    as_t = lambda v, d: tuple(int(x) for x in v) if v else (d,) * nd_
    return k, as_t(stride, 1), as_t(dilate, 1), as_t(pad, 0)


@register_op("im2col")
def im2col(data, kernel=None, stride=None, dilate=None, pad=None):
    """(N, C, H, W) -> (N, C*kh*kw, out_h*out_w): unfold sliding
    windows, channel-major then kernel-position row-major — the
    reference's im2col buffer layout (src/operator/nn/im2col.h), so a
    conv is im2col + one gemm."""
    (kh, kw), (sh, sw), (dh, dw), (ph, pw) = _conv_geom(
        kernel, stride, dilate, pad)
    x = jnp.pad(data, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    n, c, hp, wp = x.shape
    oh = (hp - (dh * (kh - 1) + 1)) // sh + 1
    ow = (wp - (dw * (kw - 1) + 1)) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = jax.lax.slice(
                x, (0, 0, i * dh, j * dw),
                (n, c, i * dh + (oh - 1) * sh + 1, j * dw + (ow - 1) * sw + 1),
                (1, 1, sh, sw))
            cols.append(patch)  # (n, c, oh, ow)
    out = jnp.stack(cols, axis=2)  # (n, c, kh*kw, oh, ow)
    return out.reshape(n, c * kh * kw, oh * ow)


@register_op("col2im")
def col2im(data, output_size=None, kernel=None, stride=None, dilate=None,
           pad=None):
    """(N, C*kh*kw, L) -> (N, C, H, W): scatter-add the unfolded
    windows back (im2col's adjoint, src/operator/nn/im2col.h col2im)."""
    (kh, kw), (sh, sw), (dh, dw), (ph, pw) = _conv_geom(
        kernel, stride, dilate, pad)
    H, W = (int(v) for v in output_size)
    n, ckk, L = data.shape
    c = ckk // (kh * kw)
    hp, wp = H + 2 * ph, W + 2 * pw
    oh = (hp - (dh * (kh - 1) + 1)) // sh + 1
    ow = (wp - (dw * (kw - 1) + 1)) // sw + 1
    cols = data.reshape(n, c, kh * kw, oh, ow)
    out = jnp.zeros((n, c, hp, wp), data.dtype)
    for i in range(kh):
        for j in range(kw):
            patch = cols[:, :, i * kw + j]  # (n, c, oh, ow)
            out = out.at[:, :,
                         i * dh:i * dh + (oh - 1) * sh + 1:sh,
                         j * dw:j * dw + (ow - 1) * sw + 1:sw].add(patch)
    return out[:, :, ph:ph + H, pw:pw + W]


# ----------------------------------------------------------------------
# misc
# ----------------------------------------------------------------------
@register_op("diag")
def diag(x, k=0, axis1=0, axis2=1):
    if x.ndim == 1:
        return jnp.diag(x, k=int(k))
    return jnp.diagonal(x, offset=int(k), axis1=int(axis1), axis2=int(axis2))


@register_op("smooth_l1")
def smooth_l1(x, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * jnp.square(x),
                     jnp.abs(x) - 0.5 / s2)


@register_op("hard_sigmoid")
def hard_sigmoid(x, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register_op("log_sigmoid")
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@register_op("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@register_op("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@register_op("cumsum")
def cumsum(x, axis=None, dtype=None):
    out = jnp.cumsum(x if dtype is None else x.astype(dtype_np(dtype)),
                     axis=None if axis is None else int(axis))
    return out


@register_op("digamma")
def digamma(x):
    """psi(x) (reference mshadow_op.h gamma family — backward of
    gammaln, exposed as an op as in upstream unary math)."""
    return jax.scipy.special.digamma(x)


@register_op("unravel_index", aliases=["_unravel_index"])
def unravel_index(x, shape=()):
    """Flat index -> multi-index coordinates, stacked on a leading axis
    (reference src/operator/tensor/ravel.cc UnravelIndex)."""
    dims = tuple(int(s) for s in shape)
    coords = jnp.unravel_index(x.astype(jnp.int64), dims)
    # reference infers output dtype = input dtype (ravel.cc)
    return jnp.stack(coords, axis=0).astype(x.dtype)


@register_op("ravel_multi_index", aliases=["_ravel_multi_index"])
def ravel_multi_index(x, shape=()):
    """Multi-index (leading axis = coordinates) -> flat index
    (reference src/operator/tensor/ravel.cc RavelMultiIndex). Plain
    stride arithmetic, NO range clipping — out-of-range coordinates
    produce out-of-range flat indices exactly as the reference does.
    True 64-bit arithmetic relies on the package-wide jax_enable_x64
    (set at import; without it jnp.int64 silently degrades to int32)."""
    dims = tuple(int(s) for s in shape)
    stride = 1
    flat = jnp.zeros(x.shape[1:], jnp.int64)
    for i in range(len(dims) - 1, -1, -1):
        flat = flat + x[i].astype(jnp.int64) * stride
        stride *= dims[i]
    return flat.astype(x.dtype)  # reference: output dtype = input dtype
