"""NDArray binary serialization — the ``.params`` / ``mx.nd.save`` format.

Re-implements the reference's NDArray file layout
(src/ndarray/ndarray.cc NDArray::Save/Load + c_api MXNDArraySave:
kMXAPINDArrayListMagic list header, per-array NDARRAY_V2_MAGIC blob with
storage type, shape, context, dtype and raw little-endian data) so
checkpoints written by reference MXNet load here and vice versa. The V3
(int64-shape) variant is accepted on load and is the default on save
only for arrays needing it.

Note: the reference mount was empty during the survey (SURVEY.md §0);
this layout follows upstream apache/mxnet v1.x. Round-trip is covered by
tests; cross-loading against a real reference checkpoint should be
re-verified when one is available.
"""
from __future__ import annotations

import struct

import numpy as np

from ..base import MXNetError, DTYPE_NAME_TO_CODE, DTYPE_CODE_TO_NAME, dtype_np, dtype_name
from ..context import Context, current_context
from .ndarray import NDArray, array as nd_array

LIST_MAGIC = 0x112
NDARRAY_V1_MAGIC = 0xF993FAC8
NDARRAY_V2_MAGIC = 0xF993FAC9
NDARRAY_V3_MAGIC = 0xF993FACA


def _write_shape(buf, shape, int64=False):
    buf += struct.pack("<I", len(shape))
    fmt = "<q" if int64 else "<I"
    for d in shape:
        buf += struct.pack(fmt, d)
    return buf


def _save_ndarray(arr: NDArray) -> bytes:
    npv = np.ascontiguousarray(arr.asnumpy())
    int64_shape = any(d > 0x7FFFFFFF for d in npv.shape)
    magic = NDARRAY_V3_MAGIC if int64_shape else NDARRAY_V2_MAGIC
    buf = struct.pack("<I", magic)
    buf += struct.pack("<i", 0)  # stype: kDefaultStorage
    buf = _write_shape(bytearray(buf), npv.shape, int64=int64_shape)
    # context: saved as CPU like the reference (load re-places arrays)
    buf += struct.pack("<ii", 1, 0)  # dev_type=kCPU, dev_id=0
    code = DTYPE_NAME_TO_CODE.get(dtype_name(arr.dtype))
    if code is None:
        raise MXNetError(f"cannot serialize dtype {arr.dtype}")
    buf += struct.pack("<i", code)
    if dtype_name(arr.dtype) == "bfloat16":
        buf += npv.view(np.uint16).tobytes()
    else:
        buf += npv.tobytes()
    return bytes(buf)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read(self, fmt):
        size = struct.calcsize(fmt)
        vals = struct.unpack_from(fmt, self.data, self.pos)
        self.pos += size
        return vals if len(vals) > 1 else vals[0]

    def read_bytes(self, n):
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out


def _load_ndarray(r: _Reader, ctx: Context) -> NDArray:
    magic = r.read("<I")
    if magic == NDARRAY_V1_MAGIC:
        int64_shape = False
    elif magic == NDARRAY_V2_MAGIC:
        r.read("<i")  # stype
        int64_shape = False
    elif magic == NDARRAY_V3_MAGIC:
        r.read("<i")
        int64_shape = True
    else:
        raise MXNetError(f"bad NDArray magic {magic:#x}")
    ndim = r.read("<I")
    fmt = "<q" if int64_shape else "<I"
    shape = tuple(r.read(fmt) for _ in range(ndim))
    r.read("<ii")  # dev_type, dev_id — ignored; placed on ctx
    code = r.read("<i")
    name = DTYPE_CODE_TO_NAME[code]
    if name == "bfloat16":
        import jax.numpy as jnp
        n = int(np.prod(shape)) if shape else 1
        raw = np.frombuffer(r.read_bytes(n * 2), np.uint16).reshape(shape)
        npv = raw.view(jnp.bfloat16)
    else:
        dt = np.dtype(dtype_np(name))
        n = int(np.prod(shape)) if shape else 1
        npv = np.frombuffer(r.read_bytes(n * dt.itemsize), dt).reshape(shape)
    return nd_array(npv, ctx=ctx, dtype=name)


def save(fname: str, data):
    """mx.nd.save — accepts NDArray, list of NDArray, or dict name→NDArray."""
    if isinstance(data, NDArray):
        arrays, names = [data], []
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    elif isinstance(data, (list, tuple)):
        arrays, names = list(data), []
    else:
        raise MXNetError("save expects NDArray | list | dict")

    from ..filesystem import open_uri
    with open_uri(fname, "wb") as f:
        f.write(struct.pack("<QQ", LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            f.write(_save_ndarray(a))
        f.write(struct.pack("<Q", len(names)))
        for nm in names:
            b = nm.encode("utf-8")
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load(fname: str):
    """mx.nd.load — returns list or dict matching how it was saved."""
    from ..filesystem import open_uri
    with open_uri(fname, "rb") as f:
        data = f.read()
    r = _Reader(data)
    magic, _ = r.read("<QQ")
    if magic != LIST_MAGIC:
        raise MXNetError(f"invalid NDArray file {fname!r} (magic {magic:#x})")
    count = r.read("<Q")
    ctx = current_context()
    arrays = [_load_ndarray(r, ctx) for _ in range(count)]
    n_names = r.read("<Q")
    if n_names == 0:
        return arrays
    names = []
    for _ in range(n_names):
        ln = r.read("<Q")
        names.append(r.read_bytes(ln).decode("utf-8"))
    return dict(zip(names, arrays))


# ----------------------------------------------------------------------
# Sharded (multi-host) checkpointing — the SURVEY §5.4 extension beyond
# the reference: each process writes ONLY its addressable shards, so a
# pod-sized model checkpoints without gathering weights to one host.
# Every shard file is itself a valid .params NDArray file whose entry
# names encode (param, global shape, shard start offsets).
# ----------------------------------------------------------------------
def _shard_entry_name(name, global_shape, starts):
    return f"{name}::shape={tuple(global_shape)}::start={tuple(starts)}"


def _parse_shard_entry(entry):
    name, shape_s, start_s = entry.split("::")
    shape = tuple(int(x) for x in shape_s[len("shape=("):-1].split(",") if x.strip())
    start = tuple(int(x) for x in start_s[len("start=("):-1].split(",") if x.strip())
    return name, shape, start


def save_sharded(prefix: str, data: dict):
    """Write this process's addressable shards of each (possibly
    sharded) array to ``{prefix}.shard-R-of-N.params``. Replicated
    values are written once (replica_id 0 only). All processes must
    call this (SPMD)."""
    import jax

    from .ndarray import _wrap

    rank, nproc = jax.process_index(), jax.process_count()
    entries = {}
    for name, arr in data.items():
        ja = arr._data
        gshape = ja.shape
        for s in ja.addressable_shards:
            if s.replica_id != 0:
                continue
            starts = tuple((idx.start or 0) if isinstance(idx, slice) else 0
                           for idx in s.index) if s.index else (0,) * ja.ndim
            entries[_shard_entry_name(name, gshape, starts)] = \
                _wrap(s.data, arr.ctx)
    fname = f"{prefix}.shard-{rank:05d}-of-{nproc:05d}.params"
    save(fname, entries)
    return fname


def load_sharded(prefix: str, ctx: Context | None = None) -> dict:
    """Reassemble a sharded checkpoint written by :func:`save_sharded`.
    Reads every shard file under the prefix (single reader or each host
    reading all shards — loading only local shards is an optimization
    for the trainer restore path)."""
    import fnmatch

    from ..filesystem import list_prefix

    files = sorted(f for f in list_prefix(f"{prefix}.shard-")
                   if fnmatch.fnmatch(f, f"{prefix}.shard-*.params"))
    if not files:
        raise MXNetError(f"no shard files found for prefix {prefix!r}")
    buffers: dict = {}
    for f in files:
        for entry, arr in load(f).items():
            name, gshape, start = _parse_shard_entry(entry)
            npv = arr.asnumpy()
            if name not in buffers:
                buffers[name] = np.zeros(gshape, npv.dtype)
            sel = tuple(slice(st, st + sz) for st, sz in zip(start, npv.shape))
            buffers[name][sel] = npv
    ctx = ctx or current_context()
    return {k: nd_array(v, ctx=ctx) for k, v in buffers.items()}


def save_bytes(data) -> bytes:
    """In-memory variant (MXNDArraySaveRawBytes analog)."""
    import io
    import tempfile, os
    # reuse the file writer via a temp buffer
    buf = bytearray()
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        arrays, names = list(data), []
    buf += struct.pack("<QQ", LIST_MAGIC, 0)
    buf += struct.pack("<Q", len(arrays))
    for a in arrays:
        buf += _save_ndarray(a)
    buf += struct.pack("<Q", len(names))
    for nm in names:
        b = nm.encode("utf-8")
        buf += struct.pack("<Q", len(b))
        buf += b
    return bytes(buf)
