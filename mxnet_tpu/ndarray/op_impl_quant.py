"""INT8 quantized compute operators.

TPU-native analog of the reference's ``src/operator/quantization/``
(quantize_v2.cc, dequantize.cc, quantized_fully_connected.cc,
quantized_conv.cc): symmetric per-tensor int8 with the matmul/conv
executed on int8 operands accumulating into int32 — on TPU the MXU
runs int8×int8→int32 natively (v5e doubles int8 throughput vs bf16),
which XLA emits when both operands are s8 and
``preferred_element_type=int32``.

Scale convention (symmetric, zero-point-free — the reference's int8
path for signed types): q = round(clip(x / s, ±127)), s = amax / 127.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .register import register_op

_QMAX = 127.0


def _amax_scale(amax):
    return jnp.maximum(jnp.asarray(amax, jnp.float32), 1e-8) / _QMAX


@register_op("quantize_v2", differentiable=False, num_visible_outputs=3)
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """Symmetric int8 quantization (reference quantize_v2.cc). With no
    calibrated range, the range is computed from the tensor (dynamic
    quantization)."""
    if min_calib_range is not None or max_calib_range is not None:
        amax = jnp.maximum(jnp.abs(jnp.asarray(min_calib_range or 0.0)),
                           jnp.abs(jnp.asarray(max_calib_range or 0.0)))
    else:
        amax = jnp.max(jnp.abs(data))
    s = _amax_scale(amax)
    q = jnp.clip(jnp.round(data / s), -_QMAX, _QMAX).astype(jnp.int8)
    return q, -amax * jnp.ones((1,), jnp.float32), amax * jnp.ones((1,), jnp.float32)


@register_op("dequantize_v2", differentiable=False)
def dequantize_v2(data, min_range, max_range, out_type="float32"):
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)).reshape(())
    return data.astype(jnp.float32) * _amax_scale(amax)


@register_op("quantized_fully_connected", differentiable=False)
def quantized_fully_connected(data, weight, x_scale, w_scale, bias=None,
                              num_hidden=None, flatten=True, no_bias=False):
    """int8 FC: s8 × s8 → s32 on the MXU, dequantized by the combined
    scale; bias (f32) added after (reference quantized_fully_connected
    with float bias path)."""
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    acc = lax.dot_general(
        x, weight, (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (x_scale.reshape(()) * w_scale.reshape(()))
    if bias is not None and not no_bias:
        out = out + bias
    return out


@register_op("quantized_conv", differentiable=False)
def quantized_conv(data, weight, x_scale, w_scale, bias=None, out_amax=None,
                   kernel=None, stride=None, dilate=None, pad=None,
                   num_filter=None, num_group=1, no_bias=False, layout=None):
    """int8 NCHW conv: s8 operands, s32 accumulation (MXU int8 path).

    ``out_amax`` (optional 6th tensor input, a (1,) f32 calibrated
    range) switches on the REQUANTIZE epilogue: the f32 result is
    rescaled by out_amax/127, rounded and clamped back to s8 — the
    tensor between chained int8 layers then stays s8 end-to-end
    (half the HBM bytes of bf16; reference mkldnn int8 fuses
    requantize into the conv the same way)."""
    nd_ = len(kernel) if kernel is not None else weight.ndim - 2
    stride = tuple(stride) if stride else (1,) * nd_
    dilate = tuple(dilate) if dilate else (1,) * nd_
    pad = tuple(pad) if pad else (0,) * nd_
    from .op_impl_nn import _CONV_DN
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_DN[nd_])
    acc = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(num_group),
        preferred_element_type=jnp.int32,
    )
    # w_scale: per-tensor (1,) or PER-OUT-CHANNEL (C,) — the latter is
    # what BN-folded weights need (the reference's mkldnn int8 conv is
    # channel-wise too)
    if w_scale.size == 1:
        ws = w_scale.reshape(())
    else:
        ws = w_scale.reshape((1, -1) + (1,) * nd_)
    out = acc.astype(jnp.float32) * x_scale.reshape(()) * ws
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd_)
    if out_amax is not None:
        s_out = _amax_scale(out_amax.reshape(()))
        return jnp.clip(jnp.round(out / s_out), -_QMAX, _QMAX
                        ).astype(jnp.int8)
    return out


def quantize_weight(w, channelwise=False):
    """Symmetric int8 weight quantization: (q, scale). With
    ``channelwise`` the scale is per out-channel (axis 0) — required
    for BN-folded conv weights whose per-channel magnitudes vary by
    the folded gamma/sigma factor."""
    if channelwise:
        red = tuple(range(1, w.ndim))
        amax = jnp.max(jnp.abs(w), axis=red)
        s = _amax_scale(amax)
        q = jnp.clip(jnp.round(w / s.reshape((-1,) + (1,) * (w.ndim - 1))),
                     -_QMAX, _QMAX).astype(jnp.int8)
        return q, s.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w))
    s = _amax_scale(amax)
    q = jnp.clip(jnp.round(w / s), -_QMAX, _QMAX).astype(jnp.int8)
    return q, s.reshape((1,)).astype(jnp.float32)


def quantize_act(x, amax=None):
    """Quantize activations with a calibrated (static) or computed
    (dynamic) range: (q, scale). ``amax`` may be None (dynamic), a
    python float, or a (1,) array whose value <= 0 selects dynamic —
    the array form resolves IN-GRAPH (jnp.where), so a checkpointed
    calibration range needs no host sync."""
    if amax is None:
        a = jnp.max(jnp.abs(x))
    else:
        cal = jnp.asarray(amax, jnp.float32).reshape(())
        a = jnp.where(cal > 0, cal, jnp.max(jnp.abs(x).astype(jnp.float32)))
    s = _amax_scale(a)
    q = jnp.clip(jnp.round(x / s), -_QMAX, _QMAX).astype(jnp.int8)
    return q, s.reshape((1,)).astype(jnp.float32)
