"""Sparse NDArray types: row_sparse and csr.

Analog of the reference's sparse storage support
(include/mxnet/ndarray.h storage types kRowSparseStorage/kCSRStorage,
src/operator/tensor/cast_storage-inl.h, python/mxnet/ndarray/sparse.py).

TPU-native design (SURVEY §7 phase 7): XLA has no native sparse, so a
RowSparseNDArray is an (indices, values) pair of dense jax arrays and
every sparse op is a gather/scatter/segment composition. That is
exactly how the reference's GPU kernels treat row_sparse anyway
(unique-rowid merge in src/kvstore/kvstore_local.h; sparse dot via
per-row kernels in dot-inl.cuh) — here XLA fuses the compositions.

This module carries the core types; sparse optimizer/kvstore paths land
with the Wide&Deep config.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, dtype_np
from ..context import current_context
from .ndarray import NDArray, _wrap, array as _dense_array


class BaseSparseNDArray(NDArray):
    """Common base for sparse storage types."""

    __slots__ = ("_aux", "_shape")

    @property
    def stype(self):
        raise NotImplementedError

    def asnumpy(self):
        return self.todense().asnumpy()

    def todense(self) -> NDArray:
        return tostype_dense(self)

    def tostype(self, stype):
        if stype == self.stype:
            return self
        return cast_storage(self, stype)


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: a subset of rows present; `indices` sorted unique int64
    row ids, `data` of shape (len(indices),) + dense_shape[1:]."""

    __slots__ = ()

    def __init__(self, data, indices, shape, ctx=None):
        # _data holds values; _aux holds indices
        super().__init__(data, ctx or current_context())
        self._aux = indices
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, v):
        self._shape = tuple(v)

    @property
    def indices(self) -> NDArray:
        return _wrap(self._aux, self._ctx)

    @property
    def data(self) -> NDArray:
        return _wrap(self._data, self._ctx)

    def __repr__(self):
        return (f"\n<RowSparseNDArray {'x'.join(map(str, self.shape))} "
                f"({self._aux.shape[0]} rows) @{self._ctx}>")


class CSRNDArray(BaseSparseNDArray):
    """csr: 2-D compressed sparse row."""

    __slots__ = ("_indptr",)

    def __init__(self, data, indices, indptr, shape, ctx=None):
        super().__init__(data, ctx or current_context())
        self._aux = indices
        self._indptr = indptr
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "csr"

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, v):
        self._shape = tuple(v)

    @property
    def indices(self) -> NDArray:
        return _wrap(self._aux, self._ctx)

    @property
    def indptr(self) -> NDArray:
        return _wrap(self._indptr, self._ctx)

    @property
    def data(self) -> NDArray:
        return _wrap(self._data, self._ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices) or a dense source."""
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = jnp.asarray(np.asarray(data), dtype_np(dtype) if dtype else None)
        indices = jnp.asarray(np.asarray(indices), jnp.int64)
        out = RowSparseNDArray.__new__(RowSparseNDArray)
        NDArray.__init__(out, data, ctx)
        out._aux = indices
        out.shape = shape if shape is not None else (int(indices.max()) + 1,) + data.shape[1:]
        return out
    dense = _dense_array(arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        out = CSRNDArray.__new__(CSRNDArray)
        NDArray.__init__(out, jnp.asarray(np.asarray(data), dtype_np(dtype) if dtype else None), ctx)
        out._aux = jnp.asarray(np.asarray(indices), jnp.int64)
        out._indptr = jnp.asarray(np.asarray(indptr), jnp.int64)
        if shape is None:
            raise MXNetError("csr_matrix from (data, indices, indptr) needs shape")
        out.shape = shape
        return out
    dense = _dense_array(arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "csr")


def tostype_dense(sp) -> NDArray:
    if isinstance(sp, RowSparseNDArray):
        out = jnp.zeros(sp.shape, sp._data.dtype)
        out = out.at[sp._aux].set(sp._data)
        return _wrap(out, sp._ctx)
    if isinstance(sp, CSRNDArray):
        m, n = sp.shape
        indptr = np.asarray(sp._indptr)
        rows = np.repeat(np.arange(m), np.diff(indptr))
        out = jnp.zeros((m, n), sp._data.dtype)
        out = out.at[jnp.asarray(rows), sp._aux].set(sp._data)
        return _wrap(out, sp._ctx)
    return sp


def cast_storage(arr, stype):
    """reference: src/operator/tensor/cast_storage-inl.h"""
    if stype == "default":
        return tostype_dense(arr)
    if stype == "row_sparse":
        dense = arr if not isinstance(arr, BaseSparseNDArray) else tostype_dense(arr)
        npv = dense.asnumpy()
        nz = np.where(np.any(npv.reshape(npv.shape[0], -1) != 0, axis=1))[0]
        out = RowSparseNDArray.__new__(RowSparseNDArray)
        NDArray.__init__(out, jnp.asarray(npv[nz]), dense._ctx)
        out._aux = jnp.asarray(nz, jnp.int64)
        out.shape = dense.shape
        return out
    if stype == "csr":
        dense = arr if not isinstance(arr, BaseSparseNDArray) else tostype_dense(arr)
        npv = dense.asnumpy()
        if npv.ndim != 2:
            raise MXNetError("csr requires 2-D")
        rows, cols = np.nonzero(npv)
        indptr = np.searchsorted(rows, np.arange(npv.shape[0] + 1))
        out = CSRNDArray.__new__(CSRNDArray)
        NDArray.__init__(out, jnp.asarray(npv[rows, cols]), dense._ctx)
        out._aux = jnp.asarray(cols, jnp.int64)
        out._indptr = jnp.asarray(indptr, jnp.int64)
        out.shape = npv.shape
        return out
    raise MXNetError(f"unknown stype {stype}")


def zeros(stype, shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    dt = dtype_np(dtype)
    if stype == "row_sparse":
        out = RowSparseNDArray.__new__(RowSparseNDArray)
        NDArray.__init__(out, jnp.zeros((0,) + tuple(shape[1:]), dt), ctx)
        out._aux = jnp.zeros((0,), jnp.int64)
        out.shape = tuple(shape)
        return out
    if stype == "csr":
        out = CSRNDArray.__new__(CSRNDArray)
        NDArray.__init__(out, jnp.zeros((0,), dt), ctx)
        out._aux = jnp.zeros((0,), jnp.int64)
        out._indptr = jnp.zeros((shape[0] + 1,), jnp.int64)
        out.shape = tuple(shape)
        return out
    from . import zeros as dzeros
    return dzeros(shape, ctx, dtype)


def retain(data, indices):
    """sparse_retain: keep only the given rows of a RowSparseNDArray."""
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("retain expects row_sparse input")
    want = jnp.asarray(np.asarray(indices.asnumpy() if isinstance(indices, NDArray) else indices),
                       jnp.int64)
    mask = jnp.isin(data._aux, want)
    keep = np.where(np.asarray(mask))[0]
    out = RowSparseNDArray.__new__(RowSparseNDArray)
    NDArray.__init__(out, data._data[jnp.asarray(keep)], data._ctx)
    out._aux = data._aux[jnp.asarray(keep)]
    out.shape = data.shape
    return out


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (reference dot-inl.h sparse branches).

    TPU-native fast paths never materialize the (batch, num_features)
    dense lhs (which for Criteo-scale feature spaces would not fit):
    - ``dot(csr, dense)``: gather rhs rows by the csr column ids,
      scale by the values, scatter-add by row — one gather + one
      segment-sum, fully on-device (the reference's DotCsrDnsDns
      warp-per-row GPU kernel plays this role, dot-inl.cuh).
    - ``dot(csr.T, dense)``: scatter-add contributions into a dense
      (num_features, n) result (DotCsrTransDnsDns analog) — callers
      wanting the row_sparse gradient form use retain/row_sparse_array
      on the result rows they touched.
    """
    from . import dot as dense_dot
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs, BaseSparseNDArray) \
            and not transpose_b and rhs.ndim == 2:
        m, _ = lhs.shape
        indptr = np.asarray(lhs._indptr)
        rows = jnp.asarray(np.repeat(np.arange(m), np.diff(indptr)))
        if transpose_a:
            n_out = lhs.shape[1]
            gathered = rhs._data[rows] * lhs._data[:, None].astype(rhs.dtype)
            out = jnp.zeros((n_out, rhs.shape[1]), rhs.dtype) \
                .at[lhs._aux].add(gathered)
        else:
            contrib = rhs._data[lhs._aux] \
                * lhs._data[:, None].astype(rhs.dtype)
            out = jnp.zeros((m, rhs.shape[1]), rhs.dtype).at[rows].add(contrib)
        return _wrap(out, lhs._ctx)
    if isinstance(lhs, BaseSparseNDArray):
        lhs = tostype_dense(lhs)
    if isinstance(rhs, BaseSparseNDArray):
        rhs = tostype_dense(rhs)
    return dense_dot(lhs, rhs, transpose_a=transpose_a, transpose_b=transpose_b)


def csr_to_ell(csr, k=None):
    """Convert a CSR batch to fixed-width padded gather form — (column
    ids (B, k) int32, values (B, k)) with zero padding.

    The TPU-first representation of a sparse batch: every downstream op
    is a static-shape gather/einsum (the Wide&Deep fused-field
    pattern), so jit compiles ONCE for all batches when ``k`` is fixed
    (e.g. ``LibSVMIter.max_row_nnz``). Rows denser than ``k`` raise.
    """
    indptr = np.asarray(csr._indptr)
    lens = np.diff(indptr)
    if k is None:
        k = int(lens.max()) if lens.size else 1
    if lens.size and int(lens.max()) > k:
        raise MXNetError(f"csr_to_ell: a row has {int(lens.max())} nnz > "
                         f"k={k}")
    b = csr.shape[0]
    rows = np.repeat(np.arange(b), lens)
    pos = np.arange(indptr[-1]) - np.repeat(indptr[:-1], lens)
    cols = np.zeros((b, k), np.int32)
    vals = np.zeros((b, k), np.asarray(csr._data).dtype)
    cols[rows, pos] = np.asarray(csr._aux)
    vals[rows, pos] = np.asarray(csr._data)
    return (_dense_array(cols, ctx=csr._ctx),
            _dense_array(vals, ctx=csr._ctx))


# ----------------------------------------------------------------------
# Lazy sparse optimizer updates (reference optimizer_op.cc row_sparse
# FComputeEx branches: SGDUpdateRspImpl / SGDMomLazyUpdateRspImpl /
# AdamLazyUpdateRspImpl). Only the rows present in the row_sparse grad
# are touched — on TPU these lower to one gather + fused math + one
# scatter, which XLA keeps entirely on-chip.
# ----------------------------------------------------------------------
def _prep_sparse_grad(grad, rescale_grad, clip_gradient):
    idx = grad._aux
    g = grad._data * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return idx, g


def sgd_update_rsp(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=None):
    """weight[rows] -= lr * (g + wd * weight[rows]); other rows untouched."""
    idx, g = _prep_sparse_grad(grad, rescale_grad, clip_gradient)
    w = weight._data
    rows = w[idx]
    new = rows - lr * (g.astype(rows.dtype) + wd * rows)
    weight._set_data(w.at[idx].set(new))
    return weight


def sgd_mom_update_rsp(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                       rescale_grad=1.0, clip_gradient=None,
                       lazy_update=True):
    """Lazy momentum: only touched rows decay their momentum (reference
    SGDMomLazyUpdateRspImpl semantics when lazy_update=True)."""
    idx, g = _prep_sparse_grad(grad, rescale_grad, clip_gradient)
    w, m = weight._data, mom._data
    rows_w, rows_m = w[idx], m[idx]
    # lr-inside convention, matching the dense sgd_mom_update op (and the
    # reference SGDMomLazyUpdateRspImpl) so momentum state stays
    # interchangeable with the dense path under any lr schedule.
    new_m = momentum * rows_m - lr * (g.astype(rows_w.dtype) + wd * rows_w)
    new_w = rows_w + new_m
    mom._set_data(m.at[idx].set(new_m))
    weight._set_data(w.at[idx].set(new_w))
    return weight


def adam_update_rsp(weight, grad, mean, var, lr=0.001, beta1=0.9,
                    beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                    clip_gradient=None, lazy_update=True):
    idx, g = _prep_sparse_grad(grad, rescale_grad, clip_gradient)
    w, m, v = weight._data, mean._data, var._data
    rows_w = w[idx]
    g = g.astype(rows_w.dtype) + wd * rows_w
    new_m = beta1 * m[idx] + (1.0 - beta1) * g
    new_v = beta2 * v[idx] + (1.0 - beta2) * g * g
    new_w = rows_w - lr * new_m / (jnp.sqrt(new_v) + epsilon)
    mean._set_data(m.at[idx].set(new_m))
    var._set_data(v.at[idx].set(new_v))
    weight._set_data(w.at[idx].set(new_w))
    return weight


def adagrad_update_rsp(weight, grad, history, lr=0.01, epsilon=1e-7,
                       wd=0.0, rescale_grad=1.0, clip_gradient=None):
    idx, g = _prep_sparse_grad(grad, rescale_grad, clip_gradient)
    w, h = weight._data, history._data
    rows_w = w[idx]
    g = g.astype(rows_w.dtype)
    new_h = h[idx] + g * g
    new_w = rows_w - lr * (g / jnp.sqrt(new_h + epsilon) + wd * rows_w)
    history._set_data(h.at[idx].set(new_h))
    weight._set_data(w.at[idx].set(new_w))
    return weight
