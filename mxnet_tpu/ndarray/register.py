"""Operator registry + imperative dispatch.

This is the TPU-native analog of three reference layers at once:

- the nnvm op registry (``NNVM_REGISTER_OP`` + attr dicts,
  include/mxnet/op_attr_types.h): here an :class:`Op` record holding the
  JAX implementation (the ``FCompute<tpu>`` of the north star) plus
  metadata (differentiability, number of outputs, aliases);
- ``Imperative::Invoke`` (src/imperative/imperative.cc): eager dispatch —
  resolve the target context, unwrap NDArray→jax.Array, run the impl
  (shape/dtype inference is implicit: XLA infers during tracing, the
  ``SetShapeType`` analog), wrap outputs, honour ``out=``;
- ``Imperative::RecordOp``: when autograd is recording and any input
  requires grad, the op is executed through ``jax.vjp`` and the pullback
  closure is appended to the tape (the nnvm-tape analog; residuals live
  on device).

Import-time namespace codegen (``_init_op_module`` in the reference's
python/mxnet/base.py) is :func:`populate_namespace`, which turns every
registered op into a module-level function ``mx.nd.<op>``.

Async contract: dispatch returns immediately — jax.Array is a future —
and ``engine.on_dispatch`` tracks outputs for WaitForAll (see engine.py).
"""
from __future__ import annotations

import ast
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, _Registry, dtype_np
from ..context import Context, current_context
from ..engine import engine

__all__ = ["Op", "register_op", "invoke", "populate_namespace", "OP_REGISTRY"]

OP_REGISTRY = _Registry("operator")
# case-sensitive primary index (MXNet op names are case-sensitive:
# FullyConnected vs broadcast_add)
_OPS: dict[str, "Op"] = {}


class Op:
    """A registered operator.

    Attributes
    ----------
    name : canonical op name (e.g. 'FullyConnected')
    fn : callable(*arrays, **params) -> array | tuple(arrays)
        Pure JAX implementation; must be jit-traceable.
    differentiable : bool
        If False the op is never recorded on the autograd tape
        (integer/ordering ops). Analog of having no FGradient attr.
    num_visible_outputs : int | None
        When the impl returns a tuple but user-facing output count is
        smaller (e.g. BatchNorm returns (out, mean, var)), how many lead
        outputs the eager API returns. None = all.
    """

    __slots__ = ("name", "fn", "differentiable", "aliases",
                 "num_visible_outputs", "mutates", "dynamic_arity",
                 "infer_num_outputs", "infer_input_names")

    def __init__(self, name, fn, differentiable=True, aliases=(),
                 num_visible_outputs=None, mutates=(), dynamic_arity=False,
                 infer_num_outputs=None, infer_input_names=None):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        self.aliases = tuple(aliases)
        self.num_visible_outputs = num_visible_outputs
        # (raw_output_index, input_index) pairs written back in place —
        # the reference's kWriteInplace/aux-state mutation (optimizer ops
        # update mom/mean/var inputs; see op_impl_optimizer.py)
        self.mutates = tuple(mutates)
        # True only for ops whose ``num_outputs`` kwarg IS the output
        # count (split/SliceChannel, amp_multicast); gates the symbolic
        # arity override so an unrelated param named num_outputs on a
        # future op can't silently mis-route sym[i] indexing
        self.dynamic_arity = bool(dynamic_arity)
        # param-dependent metadata hooks (mx.operator Custom: output
        # count and input names come from the user's CustomOpProp, keyed
        # by the op_type param) — callable(params_dict) -> int / [str]
        self.infer_num_outputs = infer_num_outputs
        self.infer_input_names = infer_input_names

    def __repr__(self):
        return f"<Op {self.name}>"


def register_op(name=None, *, differentiable=True, aliases=(),
                num_visible_outputs=None, mutates=(), wrap=True,
                dynamic_arity=False, infer_num_outputs=None,
                infer_input_names=None):
    """Decorator: register a JAX function as an operator.

    ``wrap=False`` registers the op but does not expose a generated
    namespace function (for internal helpers).
    """

    def deco(fn):
        op_name = name or fn.__name__
        op = Op(op_name, fn, differentiable=differentiable, aliases=aliases,
                num_visible_outputs=num_visible_outputs, mutates=mutates,
                dynamic_arity=dynamic_arity,
                infer_num_outputs=infer_num_outputs,
                infer_input_names=infer_input_names)
        _OPS[op_name] = op
        # re-registration may change the impl signature — drop the
        # cached positional-name tuple call_op_fn binds with
        _POS_PARAM_NAMES.pop(op_name, None)
        for a in aliases:
            _OPS[a] = op
            _POS_PARAM_NAMES.pop(a, None)
        OP_REGISTRY.register(op_name)(op)
        fn._op = op
        fn._expose = wrap
        return fn

    return deco


def get_op(name: str) -> Op:
    try:
        return _OPS[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered") from None


def list_ops():
    """Analog of MXListAllOpNames."""
    return sorted(_OPS)


def _parse_param(v):
    """Accept MXNet-style stringified params ("(3, 3)", "True", "float32")."""
    if isinstance(v, str):
        try:
            return ast.literal_eval(v)
        except (ValueError, SyntaxError):
            return v
    return v


def _as_jax(x, ctx: Context | None):
    """Unwrap NDArray / coerce python scalars & numpy to jax arrays."""
    from .ndarray import NDArray

    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (jnp.ndarray, jax.Array)):
        return x
    if isinstance(x, (int, float, bool, np.generic)):
        return x  # let jnp broadcast python scalars (keeps weak typing)
    if isinstance(x, np.ndarray):
        return jnp.asarray(x)
    raise MXNetError(f"cannot convert {type(x)} to tensor input")


# AMP dispatch-cast hook (contrib/amp): when installed, every op's
# tensor inputs pass through it before execution — the TPU-native form
# of the reference's amp_cast/amp_multicast graph rewrite. It applies
# during BOTH eager dispatch and hybridize/CachedOp tracing (traces run
# through invoke), so compiled graphs carry the casts.
_DISPATCH_CAST_HOOK = None
# bumped on every hook change: compiled-graph caches (CachedOp, the
# symbolic executor) key on this so traces built before amp.init() are
# not served after it (and vice versa)
_DISPATCH_CAST_GENERATION = 0


def set_dispatch_cast_hook(fn):
    """Install (or clear with None) the AMP cast hook:
    fn(op, [jax arrays]) -> [jax arrays]."""
    global _DISPATCH_CAST_HOOK, _DISPATCH_CAST_GENERATION
    _DISPATCH_CAST_HOOK = fn
    _DISPATCH_CAST_GENERATION += 1


def _profiler_running():
    """Cheap hot-path probe: bound once so op dispatch pays one call,
    not a module import, when profiling is off."""
    global _profiler_running
    from ..profiler import is_running
    _profiler_running = is_running
    return is_running()


def dispatch_cast_generation():
    return _DISPATCH_CAST_GENERATION


# lazy one-time bind of the np ndarray class holder (the ndarray
# PACKAGE self-aliases its `ndarray` attr, so the defining module is
# fetched through sys.modules once, not per dispatch)
_ND_NDARRAY_MOD = None


def _np_cls():
    global _ND_NDARRAY_MOD
    if _ND_NDARRAY_MOD is None:
        _ND_NDARRAY_MOD = sys.modules["mxnet_tpu.ndarray.ndarray"]
    return _ND_NDARRAY_MOD._NP_CLS


# -- op-invocation recording ------------------------------------------
# The test suite's coverage gate used to trust a hand-maintained list;
# now conftest.py turns recording on and gates on the ops ACTUALLY
# dispatched during the run (eager invoke + symbolic executor).
_INVOCATION_RECORD = None


def record_invocations(target):
    """Route every subsequent op dispatch's canonical name into
    ``target`` (a set); pass None to stop recording."""
    global _INVOCATION_RECORD
    _INVOCATION_RECORD = target


def _note_invocation(op):
    if _INVOCATION_RECORD is not None:
        _INVOCATION_RECORD.add(op.name)


def invoke(op: Op, inputs, params=None, out=None, ctx: Context | None = None,
           name=None, wrap_cls=None):
    """Eager dispatch of one op — `Imperative::Invoke` analog.

    Parameters
    ----------
    inputs : sequence of NDArray / array-like tensor inputs
    params : dict of non-tensor attributes (the DMLC parameter struct)
    out : optional NDArray (or list) to write results into (in-place API)
    ctx : target context; defaults to first input's context else current
    """
    from .ndarray import NDArray, _wrap

    _note_invocation(op)
    params = {k: _parse_param(v) for k, v in (params or {}).items() if v is not None}
    # trailing None tensor inputs (e.g. bias with no_bias=True) are dropped
    # so the impl's defaults apply — mirrors optional op inputs upstream
    while inputs and inputs[-1] is None:
        inputs = list(inputs)[:-1]

    if ctx is None:
        for x in inputs:
            if isinstance(x, NDArray):
                ctx = x.ctx
                break
        else:
            ctx = current_context()

    arrays = [_as_jax(x, ctx) for x in inputs]

    from .. import autograd  # late import (cycle)

    # The reference tapes every op invoked under record() (RecordOp),
    # which is what makes post-hoc autograd.grad(heads, variables) work;
    # backward only walks the needed subgraph.
    record = (
        autograd.is_recording()
        and op.differentiable
        and any(isinstance(x, NDArray) for x in inputs)
    )

    profiling = _profiler_running()
    if profiling:
        from .. import profiler as _profiler
        t0_us = time.perf_counter_ns() // 1000
    device = ctx.jax_device
    with jax.default_device(device):
        if record:
            fn = functools.partial(_call_positional, op, params, len(arrays))
            raw_out, vjp_fn = jax.vjp(fn, *arrays)
        else:
            raw_out = _call_positional(op, params, len(arrays), *arrays)
            vjp_fn = None
    if profiling:
        # dispatch-side op event (ThreadedEngine ProfileOperator analog;
        # device timeline comes from the XProf delegation — execution is
        # async under PJRT, so this measures trace+dispatch, which equals
        # execution under MXNET_ENGINE_TYPE=NaiveEngine)
        _profiler.record_op(op.name, t0_us, time.perf_counter_ns() // 1000)

    multi = isinstance(raw_out, (tuple, list))
    out_arrays = list(raw_out) if multi else [raw_out]
    engine.on_dispatch(out_arrays)

    # snapshot input value-keys BEFORE any out=/mutates write-back bumps
    # versions — the tape must reference the values the op actually read
    if record:
        in_keys = [(id(x), x._version) if isinstance(x, NDArray) else None
                   for x in inputs]

    # in-place state mutation (optimizer mom/mean/var — kWriteInplace)
    for out_idx, in_idx in op.mutates:
        tgt = inputs[in_idx]
        if isinstance(tgt, NDArray):
            tgt._set_data(out_arrays[out_idx])

    # wrap / write into `out`
    visible = op.num_visible_outputs
    if out is not None:
        outs = out if isinstance(out, (tuple, list)) else [out]
        vis = out_arrays if visible is None else out_arrays[:visible]
        if len(outs) != len(vis):
            raise MXNetError(f"{op.name}: expected {len(vis)} out= arrays, got {len(outs)}")
        for o, a in zip(outs, vis):
            o._set_data(a)
        results = list(outs)
    else:
        n = len(out_arrays) if visible is None else visible
        if wrap_cls is None:
            # np-mode class preservation: outputs are mx.np.ndarray when
            # any input already is one (mixing np activations with
            # classic params inside Gluon blocks keeps the np-ness of
            # the dataflow). The set_np global mode is handled inside
            # _wrap itself, so only the input rule lives here.
            np_cls = _np_cls()
            if np_cls is not None and any(isinstance(x, np_cls) for x in inputs):
                wrap_cls = np_cls
        results = [_wrap(a, ctx, cls=wrap_cls) for a in out_arrays[:n]]

    if record:
        raw_avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in out_arrays]
        autograd._record_op(op, [x for x in inputs], results, vjp_fn,
                            raw_multi=multi, n_raw_out=len(out_arrays),
                            raw_avals=raw_avals, in_keys=in_keys)

    if len(results) == 1:
        return results[0]
    return results


# op name -> leading positional parameter names of its impl (cached;
# stops at *args / keyword-only, same rule as the symbol builder's
# scalar folding)
_POS_PARAM_NAMES: dict[str, tuple] = {}


def _positional_names(op):
    names = _POS_PARAM_NAMES.get(op.name)
    if names is None:
        import inspect
        try:
            names = []
            for p in inspect.signature(op.fn).parameters.values():
                if p.kind not in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD):
                    break
                names.append(p.name)
            names = tuple(names)
        except (TypeError, ValueError):
            names = ()
        _POS_PARAM_NAMES[op.name] = names
    return names


def call_op_fn(op, arrays, params):
    """``op.fn(*arrays, **params)`` with signature-aware rebinding.

    The symbol builder folds scalar positionals into attrs by their
    ORIGINAL argument index (sym.op(x, 2.0, y) -> inputs [x, y], attr
    {<param1>: 2.0}). Calling the impl with the tensors positional
    would then bind y into the scalar's slot and collide ("multiple
    values for <param1>"). When an attr names one of the leading slots
    the tensors would occupy, walk the signature's positional names and
    the tensors together, skipping names the attrs own — reproducing
    the user's original argument order."""
    if params:
        names = _positional_names(op)
        if names and any(n in params for n in names[:len(arrays)]):
            free = [n for n in names if n not in params]
            if len(arrays) <= len(free):  # every tensor has a named slot
                return op.fn(**dict(zip(free, arrays)), **params)
    return op.fn(*arrays, **params)


def _call_positional(op, params, nargs, *arrays):
    """Closure helper so jax.vjp sees only tensor positionals. The AMP
    cast hook applies HERE — inside the differentiated function — so
    vjp transposes the casts and cotangent dtypes line up with each
    producer's output dtype."""
    if _DISPATCH_CAST_HOOK is not None:
        arrays = _DISPATCH_CAST_HOOK(op, arrays)
    return call_op_fn(op, arrays, params)


def _make_ns_function(op: Op, fname: str):
    def op_func(*args, **kwargs):
        from .ndarray import NDArray

        out = kwargs.pop("out", None)
        ctx = kwargs.pop("ctx", None)
        name = kwargs.pop("name", None)  # symbol-compat, ignored eagerly
        # split positional tensor inputs from keyword params: MXNet ops
        # take tensors positionally (or as leading kwargs like data=)
        inputs = list(args)
        # common tensor kwarg spellings (data=, lhs=, rhs=...) — pull any
        # NDArray-valued kwarg into inputs in declaration order when the
        # impl names them; simplest robust rule: NDArray kwargs are bound
        # through the impl signature directly.
        tensor_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, NDArray)}
        if tensor_kwargs and not inputs:
            # rely on python binding: call impl-style fn(data=..) via invoke
            # by reordering using fn signature
            import inspect

            sig = inspect.signature(op.fn)
            bound = []
            for pname in sig.parameters:
                if pname in tensor_kwargs:
                    bound.append(kwargs.pop(pname))
                else:
                    break
            inputs = bound
        return invoke(op, inputs, kwargs, out=out, ctx=ctx, name=name)

    op_func.__name__ = fname
    op_func.__qualname__ = fname
    op_func.__doc__ = op.fn.__doc__
    op_func._op = op
    return op_func


def populate_namespace(module_name: str, names=None):
    """Generate `mx.nd.<op>` functions into a module — `_init_op_module`.

    Called at import time by mxnet_tpu.ndarray.
    """
    mod = sys.modules[module_name]
    seen = set()
    for nm, op in list(_OPS.items()):
        if names is not None and nm not in names:
            continue
        if nm in seen:
            continue
        seen.add(nm)
        setattr(mod, nm, _make_ns_function(op, nm))
    return sorted(seen)
