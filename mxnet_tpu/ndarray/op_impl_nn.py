"""Neural-network operators.

TPU-native implementations of the reference's ``src/operator/nn/``
family (fully_connected.cc, convolution.cc, deconvolution.cc,
pooling.cc, batch_norm.cc, layer_norm.cc, softmax.cc, dropout.cc,
activation.cc, leaky_relu.cc, upsampling.cc, embedding via
indexing_op.cc) and their cuDNN variants (src/operator/nn/cudnn/*) —
here a single XLA path: conv lowers through
``lax.conv_general_dilated`` (cuDNN-autotune's job is done by XLA's
conv emitter on the MXU), pooling through ``lax.reduce_window``,
normalizations as fusable elementwise+reduce graphs. bfloat16 flows
through every op (the AMP/fp16 analog).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from ..base import dtype_np
from .register import register_op


def _tup(v, n=None):
    if v is None:
        return None
    t = tuple(int(x) for x in np.atleast_1d(v))
    if n is not None and len(t) == 1:
        t = t * n
    return t


# ----------------------------------------------------------------------
# FullyConnected (src/operator/nn/fully_connected.cc) — MXU matmul
# ----------------------------------------------------------------------
@register_op("FullyConnected")
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False, flatten=True):
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    out = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# ----------------------------------------------------------------------
# Convolution family
# ----------------------------------------------------------------------
_CONV_DN = {1: ("NCW", "OIW", "NCW"), 2: ("NCHW", "OIHW", "NCHW"),
            3: ("NCDHW", "OIDHW", "NCDHW")}

# Internal NHWC execution for 2-D convs (MXNET_TPU_CONV_NHWC=1): the API
# stays NCHW (MXNet contract) but each conv transposes to NHWC — the
# layout the TPU vector unit natively tiles — and back. Consecutive
# convs' transpose pairs cancel in XLA; measured as a bench.py knob.
# Read per call (at trace time) so setting the env before building a
# model takes effect even if mxnet_tpu was imported earlier. NOTE:
# already-compiled jit caches are keyed on shapes only — toggling the
# knob affects new traces, not cached executables.


def _conv_nhwc():
    from .. import envvars
    return envvars.get("MXNET_TPU_CONV_NHWC")


@register_op("Convolution")
def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                workspace=1024, cudnn_tune=None, cudnn_off=False, layout=None):
    nd_ = len(_tup(kernel))
    stride = _tup(stride, nd_) or (1,) * nd_
    dilate = _tup(dilate, nd_) or (1,) * nd_
    pad = _tup(pad, nd_) or (0,) * nd_
    # bf16 convs accumulate in f32 on the MXU natively; forcing
    # preferred_element_type would break the VJP's dtype contract
    if _conv_nhwc() and nd_ == 2:
        xt = jnp.transpose(data, (0, 2, 3, 1))
        wt = jnp.transpose(weight, (2, 3, 1, 0))
        dn = lax.conv_dimension_numbers(xt.shape, wt.shape,
                                        ("NHWC", "HWIO", "NHWC"))
        out = lax.conv_general_dilated(
            xt, wt,
            window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            dimension_numbers=dn,
            feature_group_count=int(num_group),
        )
        out = jnp.transpose(out, (0, 3, 1, 2))
    else:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_DN[nd_])
        out = lax.conv_general_dilated(
            data, weight,
            window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            dimension_numbers=dn,
            feature_group_count=int(num_group),
        )
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd_)
    # remat-policy anchor: under jax.checkpoint with
    # save_only_these_names('conv_out') the forward saves conv outputs
    # and recomputes only the cheap elementwise chain (BN/relu) in the
    # backward (see HybridBlock._remat_trace); a no-op otherwise
    return checkpoint_name(out, "conv_out")


@register_op("Deconvolution")
def deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, target_shape=None, num_filter=None,
                  num_group=1, no_bias=True, workspace=512, cudnn_tune=None,
                  cudnn_off=False, layout=None):
    nd_ = len(_tup(kernel))
    k = _tup(kernel)
    stride = _tup(stride, nd_) or (1,) * nd_
    dilate = _tup(dilate, nd_) or (1,) * nd_
    pad = _tup(pad, nd_) or (0,) * nd_
    adj = _tup(adj, nd_) or (0,) * nd_
    # weight layout (C_in, C_out/group, *k); flip spatial, swap in/out via
    # IOHW dimension spec → gradient-of-conv formulation
    spec = {1: "IOW", 2: "IOHW", 3: "IODHW"}[nd_]
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    (_CONV_DN[nd_][0], spec, _CONV_DN[nd_][2]))
    padding = [
        (d * (kk - 1) - p, d * (kk - 1) - p + a)
        for kk, p, d, a in zip(k, pad, dilate, adj)
    ]
    wflip = weight
    for ax in range(2, 2 + nd_):
        wflip = jnp.flip(wflip, ax)
    out = lax.conv_general_dilated(
        data, wflip,
        window_strides=(1,) * nd_,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(num_group),
    )
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd_)
    return out


# ----------------------------------------------------------------------
# Pooling (src/operator/nn/pooling.cc)
# ----------------------------------------------------------------------
@register_op("Pooling")
def pooling(data, kernel=None, pool_type="max", global_pool=False,
            pooling_convention="valid", stride=None, pad=None,
            count_include_pad=True, cudnn_off=False, layout=None):
    nd_ = data.ndim - 2
    if global_pool:
        ax = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=ax, keepdims=True)
        if pool_type == "sum":
            return jnp.sum(data, axis=ax, keepdims=True)
        return jnp.mean(data, axis=ax, keepdims=True)
    k = _tup(kernel, nd_)
    stride = _tup(stride, nd_) or (1,) * nd_
    pad = _tup(pad, nd_) or (0,) * nd_
    window = (1, 1) + k
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad high edge so the last partial window is included
        pads = []
        for i in range(nd_):
            in_sz = data.shape[2 + i]
            out_sz = int(np.ceil((in_sz + 2 * pad[i] - k[i]) / stride[i])) + 1
            needed = (out_sz - 1) * stride[i] + k[i] - in_sz - pad[i]
            pads.append((pad[i], max(needed, pad[i])))
    else:
        pads = [(p, p) for p in pad]
    padding = ((0, 0), (0, 0)) + tuple(pads)

    # init values MUST be concrete numpy scalars: under an outer jit a
    # jnp constant becomes a tracer and lax can no longer recognize the
    # max/add monoid → falls to generic reduce_window with no VJP rule
    if pool_type == "max":
        if jnp.issubdtype(data.dtype, jnp.floating):
            # NOTE: an equality-mask custom VJP (k*k shifted compares +
            # interior-padded scatter-back) was measured at b128 ResNet:
            # 1813 img/s vs 2542 with select_and_scatter — XLA does NOT
            # fuse the 9 strided-slice/pad branches and the 112^2
            # activations round-trip HBM per tap. select_and_scatter
            # stays (2.2 ms of a 46 ms step; revisit only with a real
            # Pallas window kernel).
            init = np.asarray(-np.inf, data.dtype)
        else:
            init = np.asarray(np.iinfo(data.dtype).min, data.dtype)
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    zero = np.asarray(0, data.dtype)
    summed = lax.reduce_window(data, zero, lax.add, window, strides, padding)
    if pool_type == "sum":
        return summed
    # avg
    if count_include_pad:
        denom = np.prod(k)
        return summed / np.asarray(denom, data.dtype)
    ones = jnp.ones_like(data)
    counts = lax.reduce_window(ones, zero, lax.add, window, strides, padding)
    return summed / counts


@register_op("UpSampling")
def upsampling(*args, scale=1, sample_type="nearest", num_args=1, num_filter=0,
               multi_input_mode="concat", workspace=512):
    data = args[0]
    s = int(scale)
    out = jnp.repeat(jnp.repeat(data, s, axis=2), s, axis=3)
    return out


# ----------------------------------------------------------------------
# Activations
# ----------------------------------------------------------------------
_ACT = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "log_sigmoid": jax.nn.log_sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "gelu": functools.partial(jax.nn.gelu, approximate=False),
    "gelu_tanh": functools.partial(jax.nn.gelu, approximate=True),
    "silu": jax.nn.silu,
}


@register_op("Activation")
def activation(data, act_type="relu"):
    return _ACT[act_type](data)


@register_op("LeakyReLU")
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334, _rng_key=None):
    """LeakyReLU family (src/operator/leaky_relu.cc): leaky/prelu/elu/
    selu/gelu/rrelu. GELU is the BERT-critical one (v≥1.5)."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        return jnp.where(data > 0, data, gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * (jnp.exp(data) - 1.0))
    if act_type == "selu":
        alpha, lam = 1.6732632423543772, 1.0507009873554805
        return lam * jnp.where(data > 0, data, alpha * (jnp.exp(data) - 1.0))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, mid * data)
    raise ValueError(f"unknown act_type {act_type}")


@register_op("gelu")
def gelu(data, approximate=False):
    return jax.nn.gelu(data, approximate=bool(approximate))


@register_op("swish")
def swish(data, beta=1.0):
    return data * jax.nn.sigmoid(beta * data)


# ----------------------------------------------------------------------
# softmax family (src/operator/nn/softmax.cc)
# ----------------------------------------------------------------------
@register_op("softmax")
def softmax(data, axis=-1, temperature=None, length=None, dtype=None, use_length=False):
    x = data if temperature in (None, 1.0) else data / temperature
    if length is not None:
        T = x.shape[int(axis)]
        steps = jnp.arange(T)
        mask_shape = [1] * x.ndim
        mask_shape[int(axis)] = T
        lens = length.reshape(tuple(length.shape) + (1,) * (x.ndim - length.ndim))
        mask = steps.reshape(mask_shape) < lens
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=int(axis))
        return jnp.where(mask, out, 0.0)
    out = jax.nn.softmax(x, axis=int(axis))
    if dtype is not None:
        out = out.astype(dtype_np(dtype))
    return out


@register_op("log_softmax")
def log_softmax(data, axis=-1, temperature=None, dtype=None, use_length=False):
    x = data if temperature in (None, 1.0) else data / temperature
    out = jax.nn.log_softmax(x, axis=int(axis))
    if dtype is not None:
        out = out.astype(dtype_np(dtype))
    return out


@register_op("softmin")
def softmin(data, axis=-1, temperature=None, dtype=None):
    return softmax(-data, axis=axis, temperature=temperature, dtype=dtype)


@register_op("SoftmaxActivation")
def softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@register_op("SoftmaxOutput", aliases=("Softmax",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   use_ignore=False, preserve_shape=False, multi_output=False,
                   out_grad=False, normalization="null", smooth_alpha=0.0):
    """Legacy Module-API loss head: forward=softmax, backward=p−onehot
    (reference src/operator/softmax_output.cc). Non-tensor params are
    closed over (custom_vjp args must be JAX types)."""
    ax = 1 if multi_output else -1

    @jax.custom_vjp
    def fwd(d, l):
        return jax.nn.softmax(d, axis=ax)

    def f(d, l):
        out = jax.nn.softmax(d, axis=ax)
        return out, (out, l)

    def b(res, g):
        out, l = res
        n_class = out.shape[ax]
        if multi_output and l.shape != out.shape[:1] + out.shape[2:]:
            # reference convention: flattened spatial label (n, d1*...*dk)
            l = l.reshape(out.shape[:1] + out.shape[2:])
        oh = jax.nn.one_hot(l.astype(jnp.int32), n_class, axis=ax,
                            dtype=out.dtype)
        if smooth_alpha:
            oh = oh * (1.0 - smooth_alpha) \
                + smooth_alpha / (n_class - 1) * (1.0 - oh)
        grad = out - oh
        if use_ignore:
            keep = (l != ignore_label).astype(out.dtype)
            keep = jnp.expand_dims(keep, ax) if keep.ndim < out.ndim else keep
            grad = grad * keep
        if normalization == "batch":
            grad = grad / out.shape[0]
        elif normalization == "valid" and use_ignore:
            cnt = jnp.maximum(jnp.sum(l != ignore_label), 1)
            grad = grad / cnt
        return (grad * grad_scale, jnp.zeros_like(l))

    fwd.defvjp(f, b)
    return fwd(data, label)


@register_op("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    from ..ops import pallas as _pallas

    if (_pallas.pallas_ok_for(data)
            and data.dtype in (jnp.float32, jnp.bfloat16, jnp.float16)):
        loss = _pallas.softmax_xent_fused(data, label)
        return jnp.sum(loss).reshape(1).astype(data.dtype)
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked).reshape(1)


# ----------------------------------------------------------------------
# Attention helpers for the composed (masked) path — the 4D batched
# forms of the reference-era batch_dot attention (dot-inl.h + softmax.cc)
# ----------------------------------------------------------------------
@register_op("batch_dot_attention_scores")
def batch_dot_attention_scores(query, key):
    """(B,H,Sq,D),(B,H,Sk,D) -> (B,H,Sq,Sk) score matrix (unscaled)."""
    return jnp.einsum("bhqd,bhkd->bhqk", query, key)


@register_op("batch_dot_attention_apply")
def batch_dot_attention_apply(probs, value):
    """(B,H,Sq,Sk),(B,H,Sk,D) -> (B,H,Sq,D)."""
    return jnp.einsum("bhqk,bhkd->bhqd", probs, value)


@register_op("ctc_loss", aliases=("CTCLoss", "_contrib_ctc_loss"))
def ctc_loss_op(data, label, data_lengths=None, label_lengths=None,
                use_data_lengths=False, use_label_lengths=False,
                blank_label="first"):
    """Connectionist temporal classification loss (reference
    src/operator/nn/ctc_loss.cc / warp-ctc). data (T, N, C)
    unnormalized, label (N, L). blank_label='first': index 0 is blank
    and labels use 1..C-1 (the math in ops/ctc.py); 'last': index C-1
    is blank and labels use 0..C-2 (mapped by rolling the alphabet).
    Returns (N,) losses; gradients via autodiff of the lax.scan alpha
    recursion."""
    from ..ops.ctc import ctc_loss as _ctc

    if blank_label not in ("first", "last"):
        raise ValueError(f"blank_label must be first|last, got {blank_label}")
    if blank_label == "last":
        # move blank C-1 -> 0; real classes 0..C-2 -> 1..C-1. Padding in
        # `label` for 'last' mode is -1 (reference convention) -> 0.
        data = jnp.concatenate([data[..., -1:], data[..., :-1]], axis=-1)
        label = jnp.where(label < 0, -1, label) + 1
    dl = data_lengths if use_data_lengths else None
    ll = label_lengths if use_label_lengths else None
    return _ctc(data, label, dl, ll)


@register_op("attention_length_mask")
def attention_length_mask(scores, valid_len):
    """Mask score columns at/after each example's valid length with
    -1e30 (additive-mask form of kv_lens, for the composed attention
    path; scores (B, H|1, Sq, Sk), valid_len (B,))."""
    sk = scores.shape[-1]
    m = jnp.arange(sk)[None, None, None, :] \
        < valid_len.astype(jnp.int32).reshape(-1)[:, None, None, None]
    return jnp.where(m, scores, jnp.asarray(-1e30, scores.dtype))


@register_op("attention_zero_empty_rows")
def attention_zero_empty_rows(probs, valid_len):
    """Zero the attention probs of examples whose valid_len == 0:
    softmax over an all-masked row is uniform (every score is the same
    -1e30), which would attend the padding — the flash kernel emits
    exact zeros there (l==0 guard), and the composed path must agree."""
    ok = valid_len.astype(jnp.int32).reshape(-1) > 0
    return probs * ok[:, None, None, None].astype(probs.dtype)


@register_op("attention_segment_mask")
def attention_segment_mask(scores, segment_ids):
    """Mask cross-segment score pairs with -1e30 (additive-mask form of
    the packed block-diagonal attention, for the composed path; scores
    (B, H|1, Sq, Sk), segment_ids (B, S) with Sq == Sk == S). Tokens
    attend only same-segment tokens — padding slots (id 0) are their own
    'segment', so mask them via attention_length_mask / loss masking."""
    seg = segment_ids.astype(jnp.int32)
    m = seg[:, None, :, None] == seg[:, None, None, :]
    return jnp.where(m, scores, jnp.asarray(-1e30, scores.dtype))


@register_op("attention_zero_pad_rows")
def attention_zero_pad_rows(probs, segment_ids):
    """Zero attention probs of PADDING query rows (segment id 0) in a
    packed batch: every real key is cross-segment for them, so their
    all-masked scores softmax to uniform on the composed path — the
    flash kernel emits exact zeros there (l==0 guard) and the composed
    path must agree."""
    ok = segment_ids.astype(jnp.int32) > 0
    return probs * ok[:, None, :, None].astype(probs.dtype)


@register_op("segment_valid_len", differentiable=False)
def segment_valid_len(segment_ids):
    """(B,) count of non-padding (id > 0) slots per packed row — the
    kv_lens companion a packed batch needs on the flash path (packers
    lay segments contiguously from position 0, so the count IS the used
    length)."""
    return jnp.sum((segment_ids.astype(jnp.int32) > 0)
                   .astype(jnp.int32), axis=-1)


@register_op("causal_mask_scores")
def causal_mask_scores(scores):
    """End-aligned causal mask over the last two axes of (…,Sq,Sk)."""
    sq, sk = scores.shape[-2], scores.shape[-1]
    cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
    return jnp.where(cm, scores, -1e30)


# ----------------------------------------------------------------------
# Fused scaled-dot-product attention — NEW op, no reference analog
# (SURVEY §5.7: upstream composes attention from batch_dot+softmax).
# Exposed as mx.nd.flash_attention.
# ----------------------------------------------------------------------
@register_op("flash_attention")
def flash_attention_op(query, key, value, valid_len=None, segment_ids=None,
                       causal=False, sm_scale=None):
    """softmax(Q K^T * scale) V over (B, H, S, D) inputs.

    Pallas flash kernel on TPU (O(S) memory); jnp fallback elsewhere.
    ``valid_len`` (B,) int masks keys at/after each example's length
    (padded batches) — the kernel handles it natively (per-example
    length in SMEM, fully-masked tiles skipped; see
    ops/pallas/flash_attention.py). ``segment_ids`` (B, S) int makes
    attention block-diagonal over packed sequences (sequence packing,
    io/packing.py; requires Sq == Skv): tokens attend only tokens with
    the same segment id, cross-block tiles with disjoint id ranges are
    skipped whole.
    """
    from ..ops import pallas as _pallas

    if valid_len is not None:
        valid_len = valid_len.astype(jnp.int32).reshape(-1)
    if segment_ids is not None:
        segment_ids = segment_ids.astype(jnp.int32)
    if (_pallas.pallas_ok_for(query)
            and query.dtype in (jnp.float32, jnp.bfloat16, jnp.float16)
            and query.ndim == 4):
        # end-aligned causal mask for sq != skv (KV-cache decode): q row
        # 0 is global position skv - sq, matching the tril(k=sk-sq)
        # fallback below
        q_off = key.shape[2] - query.shape[2] if causal else 0
        return _pallas.flash_attention(query, key, value, sm_scale,
                                       bool(causal), q_off, None, valid_len,
                                       segment_ids)
    d = query.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk",
                   query.astype(jnp.float32),
                   key.astype(jnp.float32)) * scale
    sq, sk = s.shape[-2], s.shape[-1]
    mask = None
    if valid_len is not None:
        mask = jnp.arange(sk)[None, None, None, :] \
            < valid_len[:, None, None, None]
    if segment_ids is not None:
        sm = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = sm if mask is None else jnp.logical_and(mask, sm)
    if causal:
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        mask = cm if mask is None else jnp.logical_and(mask, cm)
    if mask is not None:
        p = jax.nn.softmax(jnp.where(mask, s, -1e30), axis=-1)
        # fully-masked rows: emit zeros, matching the Pallas kernel's
        # l==0 guard
        p = jnp.where(
            jnp.broadcast_to(mask, s.shape).any(-1, keepdims=True), p, 0.0)
    else:
        p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      value.astype(jnp.float32)).astype(query.dtype)


# ----------------------------------------------------------------------
# normalization (batch_norm.cc, layer_norm.cc, instance_norm.cc, l2_norm)
# ----------------------------------------------------------------------
# ----------------------------------------------------------------------
# Fused training-mode BatchNorm with a hand-written VJP.
#
# The composed graph (mean pass -> centered-diff var pass -> normalize,
# autodiffed) costs ~6 full passes over the activation in backward; on
# ResNet-50 b128 the xprof trace shows every one of those fusions
# HBM-BOUND at 630-695 GB/s, so the ONLY lever is traffic. This op does
# forward in 2 passes (one fused sum/sum-of-squares reduce, one
# normalize using the E[x^2]-E[x]^2 form — the cuDNN/batch_norm.cc
# stat form — so the centered diff never materializes) and backward in
# 2 passes (one fused dbeta/dgamma reduce over (do, x), one dx pass).
# ----------------------------------------------------------------------
def _bn_red_axes(ndim, ax):
    return tuple(i for i in range(ndim) if i != ax)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _bn_train_core(x, gamma, beta, shift, eps, ax, fix_gamma):
    return _bn_train_fwd_math(x, gamma, beta, shift, eps, ax, fix_gamma)


def _bn_train_fwd_math(x, gamma, beta, shift, eps, ax, fix_gamma):
    red = _bn_red_axes(x.ndim, ax)
    n = float(np.prod([x.shape[i] for i in red]))
    shp0 = [1] * x.ndim
    shp0[ax] = -1
    # the E[u^2]-E[u]^2 form cancels catastrophically when |mean| >>
    # std; shifting u = x - shift by a per-channel estimate of the mean
    # (the layer passes the running mean — exact-identity math, zero
    # extra passes since the subtract fuses into the reduce) keeps u
    # near-centered in steady state
    xf = x.astype(jnp.float32) - shift.astype(jnp.float32).reshape(shp0)
    s1 = jnp.sum(xf, red)
    s2 = jnp.sum(xf * xf, red)  # fuses with s1: one pass, two outputs
    mean_c = s1 / n
    var = jnp.maximum(s2 / n - mean_c * mean_c, 0.0)
    mean = mean_c + shift.astype(jnp.float32)
    ivar = lax.rsqrt(var + eps)
    g32 = (jnp.ones_like(mean) if fix_gamma
           else gamma.astype(jnp.float32))
    scale = g32 * ivar
    off = beta.astype(jnp.float32) - mean_c * scale  # xf is pre-shifted
    out = (xf * scale.reshape(shp0) + off.reshape(shp0)).astype(x.dtype)
    return out, mean, var


def _bn_train_vjp_fwd(x, gamma, beta, shift, eps, ax, fix_gamma):
    out, mean, var = _bn_train_fwd_math(x, gamma, beta, shift, eps, ax,
                                        fix_gamma)
    return (out, mean, var), (x, gamma, beta, mean, var)


def _bn_train_vjp_bwd(eps, ax, fix_gamma, res, cts):
    x, gamma, beta, mean, var = res
    do, dm_out, dv_out = cts  # mean/var outputs feed (stop-gradiented)
    #                           running-stat updates; usually zero cts
    red = _bn_red_axes(x.ndim, ax)
    n = float(np.prod([x.shape[i] for i in red]))
    shp = [1] * x.ndim
    shp[ax] = -1
    ivar = lax.rsqrt(var + eps)
    g32 = (jnp.ones_like(mean) if fix_gamma
           else gamma.astype(jnp.float32))
    xf = x.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    mean_b = mean.reshape(shp)
    # pass 1 (fused): dbeta and the centered correlation in one sweep
    dbeta = jnp.sum(dof, red)
    t = jnp.sum(dof * (xf - mean_b), red)
    dgamma = t * ivar
    # pass 2: dx = a*do + c*(x - mean) + b   (per-channel a, b, c);
    # external mean/var cotangents fold into the same form:
    # d mean/dx = 1/n, d var/dx = 2(x - mean)/n
    a = g32 * ivar
    c = -a * ivar * ivar * t / n + 2.0 * dv_out.astype(jnp.float32) / n
    b = -a * dbeta / n + dm_out.astype(jnp.float32) / n
    dx = (a.reshape(shp) * dof + c.reshape(shp) * (xf - mean_b)
          + b.reshape(shp)).astype(x.dtype)
    dgamma = (jnp.zeros_like(gamma) if fix_gamma
              else dgamma.astype(gamma.dtype))
    # the stat shift is an exact mathematical no-op (and comes from the
    # non-differentiable running mean): zero cotangent
    return dx, dgamma, dbeta.astype(beta.dtype), jnp.zeros_like(mean)


_bn_train_core.defvjp(_bn_train_vjp_fwd, _bn_train_vjp_bwd)


@register_op("BatchNormTrain", wrap=False, num_visible_outputs=3)
def batch_norm_train(data, gamma, beta, shift=None, eps=1e-5, axis=1,
                     fix_gamma=False, momentum=0.9):
    """Training-mode BN: returns (out, batch_mean, batch_var) with the
    fused 2-pass forward / 2-pass backward (reference
    src/operator/nn/batch_norm.cc computes the same batch stats; the
    running-stat EMA update stays in the Gluon layer). ``shift`` is a
    per-channel mean estimate (the running mean) that re-centers the
    one-pass variance against cancellation — exact-identity math."""
    ax = int(axis) % data.ndim
    if shift is None:
        shift = jnp.zeros(data.shape[ax], jnp.float32)
    return _bn_train_core(data, gamma, beta, shift, float(eps), ax,
                          bool(fix_gamma))


@register_op("BatchNorm", wrap=False)
def batch_norm(data, gamma, beta, mean, var, eps=1e-5, momentum=0.9,
               fix_gamma=True, use_global_stats=False, output_mean_var=False,
               axis=1, cudnn_off=False):
    """Normalize with the given stats (stat selection/update is done by
    the eager wrapper or the Gluon layer — see gluon/nn/basic_layers.py)."""
    ax = int(axis) % data.ndim
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    # stats/scale may be fp32 while data is bf16 (mixed precision: the
    # cudnn path does the same) — normalize in fp32, emit data's dtype
    x_hat = (data.astype(jnp.float32)
             - mean.astype(jnp.float32).reshape(shape)) * \
        lax.rsqrt(var.astype(jnp.float32).reshape(shape) + eps)
    out = x_hat * g.astype(jnp.float32).reshape(shape) \
        + beta.astype(jnp.float32).reshape(shape)
    return out.astype(data.dtype)


@register_op("LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    ax = int(axis) % data.ndim
    # Pallas fused path (cuDNN-analog): last-axis norm, TPU dtypes only
    if (not output_mean_var and ax == data.ndim - 1
            and data.dtype in (jnp.float32, jnp.bfloat16, jnp.float16)):
        from ..ops import pallas as _pallas

        if _pallas.pallas_ok_for(data):
            return _pallas.layer_norm_fused(
                data, gamma, beta, float(eps))
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    x_hat = (data - mean) * lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    out = x_hat * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)
    return out


@register_op("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3):
    ax = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(shape) + beta.reshape(shape)


@register_op("GroupNorm")
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    n, c = data.shape[:2]
    g = int(num_groups)
    x = data.reshape((n, g, c // g) + data.shape[2:])
    ax = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=ax, keepdims=True)
    var = jnp.var(x, axis=ax, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(shape) + beta.reshape(shape)


# ----------------------------------------------------------------------
# Dropout (src/operator/nn/dropout.cc) — functional RNG via random.py
# ----------------------------------------------------------------------
@register_op("Dropout", wrap=False)
def dropout(data, p=0.5, mode="training", axes=None, _training=True, _rng_key=None):
    if not _training and mode != "always":
        return data + 0
    if p <= 0.0:
        return data + 0
    if _rng_key is None:
        from .. import random as _random
        _rng_key = _random._next_key()
    shape = list(data.shape)
    if axes:
        for a in np.atleast_1d(axes):
            shape[int(a)] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(_rng_key, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


# ----------------------------------------------------------------------
# Embedding (src/operator/tensor/indexing_op.cc Embedding)
# ----------------------------------------------------------------------
@jax.custom_vjp
def _take_rows_sorted_grad(weight, idx):
    return jnp.take(weight, idx, axis=0)


def _take_rows_fwd(weight, idx):
    # residuals must be JAX types: a zero-size slice carries the
    # table's row count and dtype without holding the table alive
    token = jnp.zeros((weight.shape[0], 0), weight.dtype)
    return jnp.take(weight, idx, axis=0), (idx, token)


def _take_rows_bwd(res, g):
    # table gradient via SORT + segment-sum instead of the default take
    # VJP's random-order scatter-add: collisions (duplicate ids in the
    # batch) serialize scatter writes on TPU, while a sorted
    # segment_sum (indices_are_sorted) accumulates each table row's
    # contributions in one linear pass — the kvstore_local.h
    # unique-rowid merge, in-graph
    idx, token = res
    flat_idx = idx.reshape(-1)
    gf = g.reshape(-1, g.shape[-1])
    order = jnp.argsort(flat_idx)
    dW = jax.ops.segment_sum(gf[order], flat_idx[order],
                             num_segments=token.shape[0],
                             indices_are_sorted=True)
    return dW.astype(token.dtype), None


_take_rows_sorted_grad.defvjp(_take_rows_fwd, _take_rows_bwd)


@jax.custom_vjp
def _take_rows_bf16_grad(weight, idx):
    return jnp.take(weight, idx, axis=0)


def _take_rows_bf16_bwd(res, g):
    # accumulate the table gradient scatter in bf16 (32B rows vs 64B
    # against the VMEM-write-bound scatter unit), densify to the
    # table's dtype after — trades collision-accumulation precision
    # for scatter bytes
    idx, token = res
    flat_idx = idx.reshape(-1)
    gf = g.reshape(-1, g.shape[-1]).astype(jnp.bfloat16)
    dW = jnp.zeros((token.shape[0], g.shape[-1]), jnp.bfloat16)
    dW = dW.at[flat_idx].add(gf)
    return dW.astype(token.dtype), None


_take_rows_bf16_grad.defvjp(_take_rows_fwd, _take_rows_bf16_bwd)


@register_op("Embedding")
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    idx = data.astype(jnp.int32)
    # MXNET_TPU_EMB_GRAD=sorted: sort+segment-sum table gradient
    # (kvstore unique-rowid merge in-graph). A/B on v5e (W&D b8192,
    # chain=10): 428.9k vs 618.1k ex/s — the 213k-row sort+permute
    # costs MORE than scatter collision serialization saves, so the
    # default stays the plain take VJP; the option remains for
    # narrow-table/high-collision workloads.
    from .. import envvars as _envvars
    mode = _envvars.get("MXNET_TPU_EMB_GRAD")
    if mode == "sorted":
        return _take_rows_sorted_grad(weight, idx)
    if mode == "bf16":
        return _take_rows_bf16_grad(weight, idx)
    return jnp.take(weight, idx, axis=0)


# ----------------------------------------------------------------------
# losses as ops
# ----------------------------------------------------------------------
@register_op("MakeLoss")
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    return data * 1.0


@register_op("LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0):
    return _regression_out(data, label, grad_scale, "linear")


@register_op("MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0):
    return _regression_out(data, label, grad_scale, "mae")


@register_op("LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0):
    return _regression_out(data, label, grad_scale, "logistic")


def _regression_out(data, label, grad_scale, kind):
    @jax.custom_vjp
    def fwd(d, l):
        return jax.nn.sigmoid(d) if kind == "logistic" else d + 0

    def f(d, l):
        return fwd(d, l), (d, l)

    def b(res, g):
        d, l = res
        out = jax.nn.sigmoid(d) if kind == "logistic" else d
        if kind == "mae":
            grad = jnp.sign(out - l)
        else:
            grad = out - l
        return (grad * grad_scale / d.shape[0] * 1.0, jnp.zeros_like(l))

    fwd.defvjp(f, b)
    return fwd(data, label)


# ----------------------------------------------------------------------
# correlation-ish / misc nn
# ----------------------------------------------------------------------
@register_op("BilinearSampler")
def bilinear_sampler(data, grid, cudnn_off=False):
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx); y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wx1 = gx - x0; wy1 = gy - y0
    wx0 = 1.0 - wx1; wy0 = 1.0 - wy1

    def sample(y, x):
        xi = jnp.clip(x, 0, w - 1).astype(jnp.int32)
        yi = jnp.clip(y, 0, h - 1).astype(jnp.int32)
        bidx = jnp.arange(n)[:, None, None]
        return data[bidx, :, yi, xi].transpose(0, 3, 1, 2)

    out = (sample(y0, x0) * (wy0 * wx0)[:, None] + sample(y0, x1) * (wy0 * wx1)[:, None]
           + sample(y1, x0) * (wy1 * wx0)[:, None] + sample(y1, x1) * (wy1 * wx1)[:, None])
    return out


@register_op("LRN", aliases=["lrn"])
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response normalization (reference src/operator/nn/lrn.cc —
    AlexNet-era cross-channel normalization):
    ``y = x / (knorm + alpha/nsize * sum_window x^2)^beta`` with the sum
    over an ``nsize`` channel window. TPU-first: the window sum is a
    conv-free cumulative-sum difference along C (one pass, XLA-fusable),
    not the reference's explicit channel loop."""
    n, c, h, w = data.shape
    half = int(nsize) // 2
    sq = (data * data).astype(jnp.float32)
    # windowed channel sum via padded cumsum difference
    cs = jnp.cumsum(jnp.pad(sq, ((0, 0), (half + 1, half), (0, 0), (0, 0))),
                    axis=1)
    win = (cs[:, nsize:] - cs[:, :-nsize])[:, :c]
    norm = (knorm + (alpha / nsize) * win) ** beta
    return (data.astype(jnp.float32) / norm).astype(data.dtype)


@register_op("ROIPooling")
def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """ROI max pooling (reference src/operator/roi_pooling.cc).
    data (N,C,H,W); rois (R,5) rows ``[batch_idx, x1, y1, x2, y2]`` in
    image coordinates. TPU-first: per-bin membership masks reduce along
    H then W as two masked maxes (static shapes, no per-roi dynamic
    slicing — XLA sees one fused program for all rois)."""
    ph, pw = (int(p) for p in pooled_size)
    n, c, h, w = data.shape
    r = rois.shape[0]
    b = rois[:, 0].astype(jnp.int32)

    def _round_c(v):
        # std::round semantics (half away from zero) — jnp.round is
        # banker's rounding and disagrees at *.5 coordinates
        return (jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)).astype(jnp.int32)

    x1 = _round_c(rois[:, 1] * spatial_scale)
    y1 = _round_c(rois[:, 2] * spatial_scale)
    x2 = _round_c(rois[:, 3] * spatial_scale)
    y2 = _round_c(rois[:, 4] * spatial_scale)
    rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
    rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)

    def bin_mask(start, extent, nbins, size):
        # mask[r, i, s]: spatial index s inside bin i of roi r
        i = jnp.arange(nbins)[None, :, None].astype(jnp.float32)
        s = jnp.arange(size)[None, None, :]
        lo = start[:, None, None] + jnp.floor(i * extent[:, None, None] / nbins)
        hi = start[:, None, None] + jnp.ceil((i + 1) * extent[:, None, None] / nbins)
        # reference clips bins to the feature map and forces >=1 cell
        hi = jnp.maximum(hi, lo + 1)
        return (s >= lo) & (s < hi) & (s >= 0) & (s < size)

    mh = bin_mask(y1, rh, ph, h)          # (R, ph, H)
    mw = bin_mask(x1, rw, pw, w)          # (R, pw, W)
    xr = data.astype(jnp.float32)[b]      # (R, C, H, W)
    neg = jnp.float32(-3.4e38)
    t = jnp.where(mh[:, None, :, :, None], xr[:, :, None], neg)  # (R,C,ph,H,W)
    t = t.max(axis=3)                     # (R, C, ph, W)
    out = jnp.where(mw[:, None, None], t[:, :, :, None], neg).max(axis=4)
    # empty rois (all cells clipped away) return 0, matching reference
    out = jnp.where(out <= neg / 2, 0.0, out)
    return out.astype(data.dtype)


@register_op("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Affine/warp sampling-grid generation (reference
    src/operator/spatial_transformer.cc GridGenerator): produces the
    normalized (x, y) grid BilinearSampler consumes."""
    th, tw = (int(t) for t in target_shape)
    if transform_type == "affine":
        if th <= 0 or tw <= 0:
            raise ValueError("GridGenerator(transform_type='affine') "
                             "requires target_shape (reference: mandatory "
                             "param)")
        n = data.shape[0]
        theta = data.reshape(n, 2, 3).astype(jnp.float32)
        ys = jnp.linspace(-1.0, 1.0, th)
        xs = jnp.linspace(-1.0, 1.0, tw)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        src = jnp.stack([gx, gy, ones], 0).reshape(3, -1)   # (3, th*tw)
        out = jnp.einsum("nij,jk->nik", theta, src)          # (n, 2, th*tw)
        return out.reshape(n, 2, th, tw)
    if transform_type == "warp":
        # data is (n, 2, h, w) flow; add to the identity pixel grid and
        # normalize to [-1, 1]
        n, _, h, w = data.shape
        gy, gx = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
        fx = (data[:, 0] + gx).astype(jnp.float32)
        fy = (data[:, 1] + gy).astype(jnp.float32)
        nx = 2.0 * fx / jnp.maximum(w - 1, 1) - 1.0
        ny = 2.0 * fy / jnp.maximum(h - 1, 1) - 1.0
        return jnp.stack([nx, ny], 1)
    raise ValueError(f"unknown transform_type {transform_type!r}")


@register_op("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=False):
    """Spatial transformer network op (reference
    src/operator/spatial_transformer.cc): affine GridGenerator feeding
    the bilinear sampler, end-to-end differentiable."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise ValueError("SpatialTransformer supports affine/bilinear only "
                         "(matches the reference)")
    grid = grid_generator(loc, transform_type="affine",
                          target_shape=target_shape)
    return bilinear_sampler(data, grid)
