"""mx.nd — the imperative NDArray namespace.

Import-time op-namespace codegen: the reference generates
``mxnet.ndarray.*`` functions from the C op registry at import
(python/mxnet/base.py ``_init_op_module`` reading MXListAllOpNames);
here :func:`register.populate_namespace` does the same from the Python
op registry.
"""
from __future__ import annotations

import sys

import numpy as _np

from .ndarray import NDArray, array, empty, zeros, ones, full, arange, _wrap
from . import register as _register

# op implementations — importing registers them
from . import op_impl_basic  # noqa: F401
from . import op_impl_nn  # noqa: F401
from . import op_impl_optimizer  # noqa: F401
from . import op_impl_random  # noqa: F401
from . import op_impl_rnn  # noqa: F401
from . import op_impl_quant  # noqa: F401
from .. import operator as _operator  # noqa: F401  (registers Custom)
from ..ops import detection as _detection  # noqa: F401  (SSD op family)
from ..ops import vision_contrib as _vision_contrib  # noqa: F401

# generate mx.nd.<op> functions into this module
_GENERATED = _register.populate_namespace(__name__)

from .register import invoke as _invoke, get_op as _get_op  # noqa: E402


def zeros_like(data, **kwargs):
    return _invoke(_get_op("zeros_like"), [data])


def ones_like(data, **kwargs):
    return _invoke(_get_op("ones_like"), [data])


# ----------------------------------------------------------------------
# stateful-op eager wrappers (training-mode injection; reference does
# this inside the op via Imperative::is_training())
# ----------------------------------------------------------------------
def Dropout(data, p=0.5, mode="training", axes=None, cudnn_off=False, **kwargs):
    from .. import autograd
    return _invoke(_get_op("Dropout"), [data],
                   {"p": p, "mode": mode, "axes": axes,
                    "_training": autograd.is_training()})


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
              momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, cudnn_off=False, **kwargs):
    """Eager BatchNorm with reference semantics: batch stats + moving-stat
    in-place update in train mode, moving stats in predict mode
    (reference src/operator/nn/batch_norm.cc aux-state update)."""
    from .. import autograd

    ax = int(axis) % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    if autograd.is_training() and not use_global_stats:
        mean = _invoke(_get_op("mean"), [data], {"axis": red})
        diff = data - mean.reshape([1 if i != ax else -1 for i in range(data.ndim)])
        var = _invoke(_get_op("mean"), [diff * diff], {"axis": red})
        with autograd.pause():
            m = float(momentum)
            moving_mean._set_data((m * moving_mean._data
                                   + (1 - m) * mean._data.astype(moving_mean.dtype)))
            moving_var._set_data((m * moving_var._data
                                  + (1 - m) * var._data.astype(moving_var.dtype)))
    else:
        mean, var = moving_mean, moving_var
    out = _invoke(_get_op("BatchNorm"), [data, gamma, beta, mean, var],
                  {"eps": eps, "momentum": momentum, "fix_gamma": fix_gamma,
                   "axis": axis})
    if output_mean_var:
        return out, mean, var
    return out


# ----------------------------------------------------------------------
# save / load (NDArray file format; serialization.py implements the
# reference binary layout — src/ndarray/ndarray.cc Save/Load)
# ----------------------------------------------------------------------
def save(fname, data):
    from .serialization import save as _save
    _save(fname, data)


def load(fname):
    from .serialization import load as _load
    return _load(fname)


def save_sharded(prefix, data):
    """Multi-host sharded checkpoint: each process writes its shards
    (serialization.py save_sharded — SURVEY §5.4 extension)."""
    from .serialization import save_sharded as _ss
    return _ss(prefix, data)


def load_sharded(prefix, ctx=None):
    from .serialization import load_sharded as _ls
    return _ls(prefix, ctx)


def concatenate(arrays, axis=0, always_copy=True):
    return _invoke(_get_op("concat"), list(arrays), {"dim": axis})


def split(data, num_outputs=1, axis=1, squeeze_axis=False):
    res = _invoke(_get_op("split"), [data],
                  {"num_outputs": num_outputs, "axis": axis,
                   "squeeze_axis": squeeze_axis})
    return res


def split_v2(data, indices_or_sections, axis=0, squeeze_axis=False):
    return _invoke(_get_op("split_v2"), [data],
                   {"indices_or_sections": indices_or_sections, "axis": axis,
                    "squeeze_axis": squeeze_axis})


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    return _invoke(_get_op("topk"), [data],
                   {"axis": axis, "k": k, "ret_typ": ret_typ,
                    "is_ascend": is_ascend, "dtype": dtype})


def waitall():
    from ..engine import engine
    engine.wait_all()


def moveaxis(data, source, destination):
    import jax.numpy as jnp
    return _wrap(jnp.moveaxis(data._data, source, destination), data.ctx)


def stack(*data, axis=0):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _invoke(_get_op("stack"), list(data), {"axis": axis})


def concat(*data, dim=1):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _invoke(_get_op("concat"), list(data), {"dim": dim})


def add_n(*data):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _invoke(_get_op("add_n"), list(data))


ElementWiseSum = add_n


# random / sparse / linalg / contrib sub-namespaces
from . import random  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import linalg  # noqa: E402,F401
from . import contrib  # noqa: E402,F401

ndarray = sys.modules[__name__]  # self-alias (mx.ndarray is mx.nd)
