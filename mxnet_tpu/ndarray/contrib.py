"""mx.nd.contrib namespace (python/mxnet/ndarray/contrib.py analog):
control flow, arange_like, and misc contrib ops."""
from __future__ import annotations

from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401
from .register import invoke as _invoke, get_op as _get_op


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    return _invoke(_get_op("_arange_like"), [data],
                   {"start": start, "step": step, "repeat": repeat,
                    "axis": axis})


def boolean_mask(data, index, axis=0):
    """Select the slices of ``data`` along ``axis`` where ``index`` is
    nonzero (reference src/operator/contrib/boolean_mask.cc).

    The output shape depends on the mask VALUES — inherently dynamic,
    so this is an eager-only op (the reference's is likewise imperative
    contrib): the mask syncs to host once, then the pick lowers to a
    single differentiable ``take`` (gradients scatter back through its
    VJP; positions masked out get zero gradient). Inside jit/hybridize
    use ``where``-style masking with a static shape instead.
    """
    import numpy as np
    from .ndarray import NDArray, array as _array

    if not isinstance(index, NDArray) or not isinstance(data, NDArray):
        raise TypeError("boolean_mask expects NDArray data and index")
    mask = index.asnumpy()
    if mask.ndim != 1:
        raise ValueError(f"index must be 1-D, got shape {mask.shape}")
    if mask.shape[0] != data.shape[int(axis)]:
        raise ValueError(
            f"boolean_mask: index length {mask.shape[0]} != data.shape"
            f"[{int(axis)}] = {data.shape[int(axis)]}")
    keep = np.flatnonzero(mask != 0).astype(np.int64)
    from . import take as _take
    return _take(data, _array(keep, ctx=data.ctx), axis=int(axis),
                 mode="clip")


def index_copy(old_tensor, index_vector, new_tensor):
    import jax.numpy as jnp
    from .ndarray import _wrap
    idx = index_vector._data.astype(jnp.int32)
    return _wrap(old_tensor._data.at[idx].set(new_tensor._data),
                 old_tensor.ctx)


def index_array(data, axes=None):
    import jax.numpy as jnp
    import numpy as np
    from .ndarray import _wrap
    shape = data.shape
    axes = tuple(np.atleast_1d(axes)) if axes is not None else tuple(range(len(shape)))
    grids = jnp.meshgrid(*[jnp.arange(shape[a]) for a in axes], indexing="ij")
    out = jnp.stack(grids, axis=-1).astype(jnp.int64)
    return _wrap(out, data.ctx)


def getnnz(data, axis=None):
    from . import sparse
    if isinstance(data, sparse.CSRNDArray):
        from .ndarray import _wrap
        import jax.numpy as jnp
        return _wrap(jnp.asarray([data._aux.shape[0]], jnp.int64), data.ctx)
    raise NotImplementedError


def quantize(data, min_range, max_range, out_type="uint8"):
    """INT8 quantization (reference src/operator/quantization/quantize.cc)."""
    import jax.numpy as jnp
    from .ndarray import _wrap
    lo = float(min_range.asscalar())
    hi = float(max_range.asscalar())
    if out_type == "uint8":
        scale = 255.0 / max(hi - lo, 1e-8)
        q = jnp.clip(jnp.round((data._data - lo) * scale), 0, 255).astype(jnp.uint8)
    else:  # int8
        scale = 127.0 / max(abs(hi), abs(lo), 1e-8)
        q = jnp.clip(jnp.round(data._data * scale), -127, 127).astype(jnp.int8)
    return (_wrap(q, data.ctx), min_range, max_range)


def dequantize(data, min_range, max_range, out_type="float32"):
    import jax.numpy as jnp
    from .ndarray import _wrap
    lo = float(min_range.asscalar())
    hi = float(max_range.asscalar())
    if data.dtype == jnp.uint8:
        scale = (hi - lo) / 255.0
        return _wrap(data._data.astype(jnp.float32) * scale + lo, data.ctx)
    scale = max(abs(hi), abs(lo)) / 127.0
    return _wrap(data._data.astype(jnp.float32) * scale, data.ctx)


# -- SSD detection family (ops/detection.py; reference
# src/operator/contrib/multibox_*.cc + bounding_box.cc) ---------------
def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    return _invoke(_get_op("_contrib_MultiBoxPrior"), [data],
                   {"sizes": sizes, "ratios": ratios, "clip": clip,
                    "steps": steps, "offsets": offsets})


def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   ignore_label=-1.0, negative_mining_ratio=-1.0,
                   negative_mining_thresh=0.5, minimum_negative_samples=0,
                   variances=(0.1, 0.1, 0.2, 0.2)):
    return _invoke(_get_op("_contrib_MultiBoxTarget"),
                   [anchor, label, cls_pred],
                   {"overlap_threshold": overlap_threshold,
                    "ignore_label": ignore_label,
                    "negative_mining_ratio": negative_mining_ratio,
                    "negative_mining_thresh": negative_mining_thresh,
                    "minimum_negative_samples": minimum_negative_samples,
                    "variances": variances})


def MultiBoxDetection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                      background_id=0, nms_threshold=0.5,
                      force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2),
                      nms_topk=-1):
    return _invoke(_get_op("_contrib_MultiBoxDetection"),
                   [cls_prob, loc_pred, anchor],
                   {"clip": clip, "threshold": threshold,
                    "background_id": background_id,
                    "nms_threshold": nms_threshold,
                    "force_suppress": force_suppress,
                    "variances": variances, "nms_topk": nms_topk})


def box_nms(data, **kwargs):
    return _invoke(_get_op("_contrib_box_nms"), [data], kwargs)


def box_iou(lhs, rhs, format="corner"):
    return _invoke(_get_op("_contrib_box_iou"), [lhs, rhs],
                   {"format": format})


def bipartite_matching(dist, is_ascend=False, threshold=None, topk=-1):
    return _invoke(_get_op("_contrib_bipartite_matching"), [dist],
                   {"is_ascend": is_ascend, "threshold": threshold,
                    "topk": topk})


# -- contrib vision tail (ops/vision_contrib.py) ----------------------
def ROIAlign(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
             sample_ratio=-1, position_sensitive=False, aligned=False):
    return _invoke(_get_op("_contrib_ROIAlign"), [data, rois],
                   {"pooled_size": pooled_size,
                    "spatial_scale": spatial_scale,
                    "sample_ratio": sample_ratio,
                    "position_sensitive": position_sensitive,
                    "aligned": aligned})


def BilinearResize2D(data, height=0, width=0, scale_height=None,
                     scale_width=None, mode="size"):
    return _invoke(_get_op("_contrib_BilinearResize2D"), [data],
                   {"height": height, "width": width,
                    "scale_height": scale_height,
                    "scale_width": scale_width, "mode": mode})


def AdaptiveAvgPooling2D(data, output_size=(1, 1)):
    return _invoke(_get_op("_contrib_AdaptiveAvgPooling2D"), [data],
                   {"output_size": output_size})


def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):
    return _invoke(_get_op("_contrib_box_decode"), [data, anchors],
                   {"std0": std0, "std1": std1, "std2": std2, "std3": std3,
                    "clip": clip, "format": format})


def box_encode(samples, matches, anchors, refs,
               means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2)):
    return _invoke(_get_op("_contrib_box_encode"),
                   [samples, matches, anchors, refs],
                   {"means": means, "stds": stds})


def DeformableConvolution(data, offset, weight, bias=None, kernel=(3, 3),
                          stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                          num_filter=0, num_group=1, num_deformable_group=1,
                          no_bias=False):
    return _invoke(_get_op("_contrib_DeformableConvolution"),
                   [data, offset, weight, bias],
                   {"kernel": kernel, "stride": stride, "dilate": dilate,
                    "pad": pad, "num_filter": num_filter,
                    "num_group": num_group,
                    "num_deformable_group": num_deformable_group,
                    "no_bias": no_bias})


def PSROIPooling(data, rois, spatial_scale=1.0, output_dim=0,
                 pooled_size=7, group_size=0):
    return _invoke(_get_op("_contrib_PSROIPooling"), [data, rois],
                   {"spatial_scale": spatial_scale, "output_dim": output_dim,
                    "pooled_size": pooled_size, "group_size": group_size})
