"""Fused optimizer update operators.

Analog of the reference's ``src/operator/optimizer_op.{cc,cu}``
(sgd_update, sgd_mom_update, mp_sgd_* multi-precision, adam_update,
ftrl_update, rmsprop_update, signsgd/signum, nag, lamb_* (v≥1.6),
multi-tensor multi_sgd_*). Each is a pure jax function; the imperative
API writes results back through ``out=`` (NDArray._set_data — the
in-place engine-write analog), and the jitted Trainer path uses them
functionally inside one XLA computation so weight/state updates fuse
into a single HBM-bandwidth-bound kernel per parameter bucket.

All ops are registered non-differentiable (the reference marks them
TIsBackward-free utility ops; one never differentiates through an
optimizer step in MXNet v1.x).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .register import register_op


def _rescale_clip(grad, rescale_grad, clip_gradient, wd=None, weight=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register_op("sgd_update", differentiable=False)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register_op("sgd_mom_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2),))
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register_op("nag_mom_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2),))
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register_op("mp_sgd_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2),))
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """fp16/bf16 weights with fp32 master copy (mp_sgd_update in reference)."""
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_w32 = weight32 - lr * (g + wd * weight32)
    return new_w32.astype(weight.dtype), new_w32


@register_op("mp_sgd_mom_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2), (2, 3)))
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True):
    g = _rescale_clip(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight32)
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register_op("adam_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2), (2, 3)))
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    return (weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon),
            new_mean, new_var)


@register_op("adamw_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2), (2, 3)))
def adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                 clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    upd = new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight
    return weight - eta * lr * upd, new_mean, new_var


@register_op("rmsprop_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2),))
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1.0 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


@register_op("rmspropalex_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2), (2, 3), (3, 4)))
def rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    gr = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1.0 - gamma1) * jnp.square(gr)
    new_g = gamma1 * g + (1.0 - gamma1) * gr
    new_delta = gamma2 * delta - lr * gr / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n, new_g, new_delta


@register_op("ftrl_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2), (2, 3)))
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
    )
    return w, new_z, new_n


@register_op("signsgd_update", differentiable=False)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register_op("signum_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2),))
def signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom - (1.0 - momentum) * g
    w = weight + lr * jnp.sign(new_mom)
    if wd_lh:
        w = w - lr * wd_lh * weight
    return w, new_mom


@register_op("adagrad_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2),))
def adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_hist = history + jnp.square(g)
    return weight - lr * (g / jnp.sqrt(new_hist + epsilon) + wd * weight), new_hist


@register_op("adadelta_update", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2), (2, 3)))
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient) + wd * weight
    new_acc_g = rho * acc_g + (1.0 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1.0 - rho) * jnp.square(delta)
    return weight - delta, new_acc_g, new_acc_delta


@register_op("lamb_update_phase1", differentiable=False, num_visible_outputs=1,
             mutates=((1, 2), (2, 3)))
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    m = new_mean / (1.0 - beta1 ** t) if bias_correction else new_mean
    v = new_var / (1.0 - beta2 ** t) if bias_correction else new_var
    return m / (jnp.sqrt(v) + epsilon) + wd * weight, new_mean, new_var


# ----------------------------------------------------------------------
# multi-tensor fused updates (reference multi_sgd_update/multi_sgd_mom_
# update/multi_mp_sgd_*: one kernel updating MANY parameters — the
# anti-small-op-overhead device for Trainer.step; here one XLA
# computation covering the whole parameter list)
# ----------------------------------------------------------------------
def _per_weight(vals, i, default):
    if vals is None:
        return default
    if isinstance(vals, (tuple, list)):
        return float(vals[i]) if i < len(vals) else default
    return float(vals)  # one scalar for all weights


@register_op("multi_sgd_update", differentiable=False)
def multi_sgd_update(*args, lrs=(), wds=(), rescale_grad=1.0,
                     clip_gradient=-1.0, num_weights=None):
    """args = (w0, g0, w1, g1, ...); returns the updated weights."""
    n = int(num_weights) if num_weights is not None else len(args) // 2
    outs = []
    for i in range(n):
        w, g = args[2 * i], args[2 * i + 1]
        gs = _rescale_clip(g, rescale_grad, clip_gradient)
        outs.append(w - _per_weight(lrs, i, 0.01)
                    * (gs + _per_weight(wds, i, 0.0) * w))
    return tuple(outs)


@register_op("multi_sgd_mom_update", differentiable=False)
def multi_sgd_mom_update(*args, lrs=(), wds=(), momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0,
                         num_weights=None):
    """args = (w0, g0, m0, w1, g1, m1, ...); returns
    (w0', m0', w1', m1', ...) — moms are written back via out=/mutates
    at the caller."""
    n = int(num_weights) if num_weights is not None else len(args) // 3
    outs = []
    for i in range(n):
        w, g, m = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        gs = _rescale_clip(g, rescale_grad, clip_gradient)
        new_m = momentum * m - _per_weight(lrs, i, 0.01) \
            * (gs + _per_weight(wds, i, 0.0) * w)
        outs.append(w + new_m)
        outs.append(new_m)
    return tuple(outs)


@register_op("multi_mp_sgd_update", differentiable=False)
def multi_mp_sgd_update(*args, lrs=(), wds=(), rescale_grad=1.0,
                        clip_gradient=-1.0, num_weights=None):
    """args = (w0, g0, w32_0, ...); returns (w0', w32_0', ...)."""
    n = int(num_weights) if num_weights is not None else len(args) // 3
    outs = []
    for i in range(n):
        w, g, w32 = args[3 * i], args[3 * i + 1], args[3 * i + 2]
        gs = _rescale_clip(g.astype(jnp.float32), rescale_grad, clip_gradient)
        new32 = w32 - _per_weight(lrs, i, 0.01) \
            * (gs + _per_weight(wds, i, 0.0) * w32)
        outs.append(new32.astype(w.dtype))
        outs.append(new32)
    return tuple(outs)


@register_op("multi_mp_sgd_mom_update", differentiable=False)
def multi_mp_sgd_mom_update(*args, lrs=(), wds=(), momentum=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            num_weights=None):
    """args = (w0, g0, m0, w32_0, ...); returns (w0', m0', w32_0', ...)."""
    n = int(num_weights) if num_weights is not None else len(args) // 4
    outs = []
    for i in range(n):
        w, g, m, w32 = args[4 * i:4 * i + 4]
        gs = _rescale_clip(g.astype(jnp.float32), rescale_grad, clip_gradient)
        new_m = momentum * m - _per_weight(lrs, i, 0.01) \
            * (gs + _per_weight(wds, i, 0.0) * w32)
        new32 = w32 + new_m
        outs.append(new32.astype(w.dtype))
        outs.append(new_m)
        outs.append(new32)
    return tuple(outs)


@register_op("lamb_update_phase2", differentiable=False)
def lamb_update_phase2(weight, g, r1, r2, lr=0.01, lower_bound=-1.0,
                       upper_bound=-1.0):
    r1v = jnp.where(r1 > 0, r1, jnp.ones_like(r1))
    r2v = jnp.where(r2 > 0, r2, jnp.ones_like(r2))
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1v / r2v, jnp.ones_like(r1))
    if lower_bound is not None and lower_bound > 0:
        ratio = jnp.maximum(ratio, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        ratio = jnp.minimum(ratio, upper_bound)
    return weight - lr * ratio * g
