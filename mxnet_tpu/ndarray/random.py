"""mx.nd.random — sampling namespace (python/mxnet/ndarray/random.py analog)."""
from __future__ import annotations

from .register import invoke as _invoke, get_op as _get_op


def _call(name, inputs, params):
    return _invoke(_get_op(name), inputs, params)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None, **kwargs):
    return _invoke(_get_op("random_uniform"), [],
                   {"low": low, "high": high, "shape": shape, "dtype": dtype},
                   out=out, ctx=ctx)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kwargs):
    return _invoke(_get_op("random_normal"), [],
                   {"loc": loc, "scale": scale, "shape": shape, "dtype": dtype},
                   out=out, ctx=ctx)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape, dtype, ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, out=None):
    return _invoke(_get_op("random_gamma"), [],
                   {"alpha": alpha, "beta": beta, "shape": shape, "dtype": dtype},
                   out=out, ctx=ctx)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, out=None):
    return _invoke(_get_op("random_exponential"), [],
                   {"lam": 1.0 / scale, "shape": shape, "dtype": dtype},
                   out=out, ctx=ctx)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, out=None):
    return _invoke(_get_op("random_poisson"), [],
                   {"lam": lam, "shape": shape, "dtype": dtype}, out=out, ctx=ctx)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None, out=None):
    return _invoke(_get_op("random_negative_binomial"), [],
                   {"k": k, "p": p, "shape": shape, "dtype": dtype}, out=out, ctx=ctx)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None):
    return _invoke(_get_op("random_randint"), [],
                   {"low": low, "high": high, "shape": shape, "dtype": dtype},
                   out=out, ctx=ctx)


def multinomial(data, shape=None, get_prob=False, dtype="int32", out=None):
    return _invoke(_get_op("sample_multinomial"), [data],
                   {"shape": shape, "get_prob": get_prob, "dtype": dtype}, out=out)


def shuffle(data, out=None):
    return _invoke(_get_op("shuffle"), [data], {}, out=out)


def bernoulli(prob=None, logit=None, shape=None, dtype="float32", ctx=None, out=None):
    inputs = [x for x in (prob, logit) if x is not None and not isinstance(x, (int, float))]
    params = {"shape": shape, "dtype": dtype}
    if not inputs:
        params["prob"] = prob
        params["logit"] = logit
    return _invoke(_get_op("bernoulli"), inputs, params, out=out, ctx=ctx)
