"""Fused RNN operator (LSTM/GRU/vanilla) — the cuDNN RNN analog.

Reference: src/operator/rnn.cc + rnn-inl.h (RNNOp stateful op behind
gluon.rnn.LSTM; cuDNN path via cudnn_rnn-inl.h `cudnnRNNForwardTraining`
with a single packed parameter vector). TPU-native design per SURVEY §7
phase 6: one ``lax.scan`` over time per layer/direction with the gate
matmuls batched into a single (G·H × I+H) MXU matmul per step; the
packed parameter layout (all i2h/h2h weights layer-major then all
biases — the cuDNN canonical layout) is preserved so checkpoint and op
signatures match the reference. XLA unrolls nothing: scan keeps compile
time flat and lets the MXU pipeline steps.

Gate order matches cuDNN/MXNet: LSTM [i, f, g, o]; GRU [r, z, n];
vanilla relu/tanh single gate.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .register import register_op

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    """Total packed parameter count (reference GetRnnParamSize)."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * dirs
        size += dirs * gates * state_size * (isz + state_size)  # weights
    size += num_layers * dirs * gates * state_size * 2  # biases
    return size


def _unpack_params(params, num_layers, input_size, state_size, bidirectional, mode):
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    H = state_size
    idx = 0
    weights = []  # [(W_i2h, W_h2h)] per (layer, dir)
    for layer in range(num_layers):
        isz = input_size if layer == 0 else H * dirs
        per_dir = []
        for _ in range(dirs):
            w_i2h = params[idx: idx + gates * H * isz].reshape(gates * H, isz)
            idx += gates * H * isz
            w_h2h = params[idx: idx + gates * H * H].reshape(gates * H, H)
            idx += gates * H * H
            per_dir.append((w_i2h, w_h2h))
        weights.append(per_dir)
    biases = []
    for layer in range(num_layers):
        per_dir = []
        for _ in range(dirs):
            b_i2h = params[idx: idx + gates * H]
            idx += gates * H
            b_h2h = params[idx: idx + gates * H]
            idx += gates * H
            per_dir.append((b_i2h, b_h2h))
        biases.append(per_dir)
    return weights, biases


def _cell_step(mode, H):
    if mode == "lstm":
        def step(carry, gin):
            h, c = carry
            i, f, g, o = jnp.split(gin, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            new_c = f * c + i * g
            new_h = o * jnp.tanh(new_c)
            return (new_h, new_c), new_h
        return step
    if mode == "gru":
        def step(carry, gin_pair):
            h = carry
            gin_x, (w_h2h, b_h2h) = gin_pair
            hg = jnp.matmul(h, w_h2h.T) + b_h2h
            rx, zx, nx = jnp.split(gin_x, 3, axis=-1)
            rh, zh, nh = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(rx + rh)
            z = jax.nn.sigmoid(zx + zh)
            n = jnp.tanh(nx + r * nh)
            new_h = (1.0 - z) * n + z * h
            return new_h, new_h
        return step
    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

    def step(carry, gin):
        h = carry
        new_h = act(gin)
        return new_h, new_h
    return step


def _run_layer(x, w_i2h, w_h2h, b_i2h, b_h2h, h0, c0, mode, reverse=False):
    """One direction of one layer. x: (T, N, I) → (T, N, H)."""
    H = h0.shape[-1]
    if reverse:
        x = jnp.flip(x, axis=0)
    # batch all input projections into one big MXU matmul: (T*N, I)·(I, G·H)
    gin_x = jnp.einsum("tni,gi->tng", x, w_i2h) + b_i2h

    if mode == "gru":
        step = _cell_step(mode, H)

        def scan_fn(h, gx):
            return step(h, (gx, (w_h2h, b_h2h)))

        h_last, out = lax.scan(scan_fn, h0, gin_x)
        c_last = None
    elif mode == "lstm":
        from ..ops.pallas._util import pallas_ok_for
        from .. import envvars as _envvars
        if pallas_ok_for(x) and _envvars.get("MXNET_TPU_FUSED_LSTM"):
            # OPT-IN fused whole-sequence kernel (weight-stationary
            # recurrent matmul + gates in VMEM, one kernel for the
            # T-step loop — the cudnn_rnn-inl.h analog). Measured on
            # the WikiText-2 LM (650x2): b128 379k tok/s vs 382k for
            # the lax.scan path, b32 140k vs 157k — XLA's unrolled
            # while-loop + fusion already wins at these shapes, so the
            # kernel is not the default; it remains available (and
            # golden-tested) for dispatch-bound deployments.
            from ..ops.pallas.lstm import lstm_layer_fused
            out, cseq = lstm_layer_fused(
                (gin_x + b_h2h).astype(x.dtype),
                w_h2h.T.astype(x.dtype), h0, c0)
            # final state = last PROCESSED step — grab it before the
            # reverse direction flips out back to forward-time order
            h_last = out[-1]
            if reverse:
                out = jnp.flip(out, axis=0)
            return out, h_last, cseq[-1].astype(c0.dtype)
        step = _cell_step(mode, H)

        def scan_fn(carry, gx):
            h, c = carry
            gin = gx + jnp.matmul(h, w_h2h.T) + b_h2h
            return step((h, c), gin)

        (h_last, c_last), out = lax.scan(scan_fn, (h0, c0), gin_x)
    else:
        step = _cell_step(mode, H)

        def scan_fn(h, gx):
            gin = gx + jnp.matmul(h, w_h2h.T) + b_h2h
            return step(h, gin)

        h_last, out = lax.scan(scan_fn, h0, gin_x)
        c_last = None
    if reverse:
        out = jnp.flip(out, axis=0)
    return out, h_last, c_last


@register_op("RNN", wrap=False,
             infer_num_outputs=lambda params:
             3 if str(params.get("mode", "lstm")) == "lstm" else 2)
def rnn(data, parameters, state, state_cell=None, sequence_length=None,
        state_size=0, num_layers=1, bidirectional=False, mode="lstm",
        p=0.0, state_outputs=False, projection_size=None,
        lstm_state_clip_min=None, lstm_state_clip_max=None,
        lstm_state_clip_nan=False, use_sequence_length=False,
        _training=False, _rng_key=None):
    """data: (T, N, I); parameters: packed flat vector; state: (L*D, N, H).
    Returns (output, state_out[, statecell_out])."""
    T, N, input_size = data.shape
    H = int(state_size)
    L = int(num_layers)
    dirs = 2 if bidirectional else 1
    weights, biases = _unpack_params(parameters, L, input_size, H,
                                     bidirectional, mode)
    x = data
    h_states = []
    c_states = []
    key = _rng_key
    for layer in range(L):
        outs = []
        for d in range(dirs):
            sidx = layer * dirs + d
            h0 = state[sidx]
            c0 = state_cell[sidx] if state_cell is not None else None
            w_i2h, w_h2h = weights[layer][d]
            b_i2h, b_h2h = biases[layer][d]
            out, h_last, c_last = _run_layer(
                x, w_i2h, w_h2h, b_i2h, b_h2h, h0, c0, mode, reverse=(d == 1))
            outs.append(out)
            h_states.append(h_last)
            if c_last is not None:
                c_states.append(c_last)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and _training and layer < L - 1:
            if key is None:
                from .. import random as _random
                key = _random._next_key()
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1.0 - p, x.shape).astype(x.dtype)
            x = x * mask / (1.0 - p)
    h_out = jnp.stack(h_states, axis=0)
    if mode == "lstm":
        c_out = jnp.stack(c_states, axis=0)
        if lstm_state_clip_min is not None and lstm_state_clip_max is not None:
            c_out = jnp.clip(c_out, lstm_state_clip_min, lstm_state_clip_max)
        return x, h_out, c_out
    return x, h_out


def pack_rnn_params(layer_params, mode):
    """Concatenate per-layer (w_i2h, w_h2h) + biases into the packed
    vector (gluon rnn_layer does this each forward; XLA fuses it away)."""
    ws = []
    bs = []
    for (w_i2h, w_h2h, b_i2h, b_h2h) in layer_params:
        ws.append(w_i2h.reshape(-1))
        ws.append(w_h2h.reshape(-1))
        bs.append(b_i2h.reshape(-1))
        bs.append(b_h2h.reshape(-1))
    return jnp.concatenate(ws + bs)
