"""Control-flow operators: foreach / while_loop / cond.

Analog of the reference's subgraph control-flow ops
(src/operator/control_flow.cc: `_foreach`, `_while_loop`, `_cond` used
via mxnet.ndarray.contrib). TPU-native design: these are thin adapters
from the MXNet callback signatures onto jax.lax.scan / while_loop /
cond, so hybridized graphs containing them compile to single XLA
loops — the reference executes the subgraph per-iteration on the
engine; XLA rolls it into the computation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ndarray import NDArray
from ..ndarray.ndarray import _wrap
from ..context import current_context

__all__ = ["foreach", "while_loop", "cond"]


def _unwrap(x):
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        return [_unwrap(i) for i in x]
    return x


def _wrap_tree(x, ctx):
    if isinstance(x, (list, tuple)):
        return [_wrap_tree(i, ctx) for i in x]
    return _wrap(x, ctx)


def foreach(body, data, init_states):
    """mx.nd.contrib.foreach: scan `body(data_t, states) -> (out, states)`
    over axis 0 of data."""
    ctx = (data[0] if isinstance(data, (list, tuple)) else data).ctx
    data_arr = _unwrap(data)
    states_arr = _unwrap(init_states)
    multi_data = isinstance(data, (list, tuple))

    def step(states, xt):
        xs = _wrap_tree(xt, ctx) if multi_data else _wrap(xt, ctx)
        st = _wrap_tree(states, ctx)
        out, new_states = body(xs, st)
        out_arr = _unwrap(out)
        return _unwrap(new_states), out_arr

    final_states, outs = lax.scan(step, states_arr, data_arr)
    outs_nd = jax.tree_util.tree_map(lambda a: _wrap(a, ctx), outs)
    states_nd = _wrap_tree(final_states, ctx)
    return outs_nd, states_nd


def while_loop(cond_fn, func, loop_vars, max_iterations=None):
    """mx.nd.contrib.while_loop. Bounded loop: XLA needs static trip
    bounds for stacked outputs, so outputs are collected up to
    max_iterations (reference has the same parameter)."""
    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations on the TPU "
                         "backend (static shapes)")
    ctx = loop_vars[0].ctx
    vars_arr = [v._data for v in loop_vars]

    def c(state):
        i, vs = state
        keep = cond_fn(*_wrap_tree(vs, ctx))
        keep_val = keep._data if isinstance(keep, NDArray) else jnp.asarray(keep)
        return jnp.logical_and(i < max_iterations,
                               keep_val.astype(bool).reshape(()))

    def b(state):
        i, vs = state
        _, new_vs = func(*_wrap_tree(vs, ctx))
        if isinstance(new_vs, NDArray):
            new_vs = [new_vs]
        return (i + 1, [v._data for v in new_vs])

    _, final = lax.while_loop(c, b, (jnp.asarray(0), vars_arr))
    return None, _wrap_tree(final, ctx)


def cond(pred_fn, then_func, else_func, inputs):
    """mx.nd.contrib.cond."""
    ctx = inputs[0].ctx
    arrs = [x._data for x in inputs]
    p = pred_fn(*_wrap_tree(arrs, ctx))
    p_val = p._data if isinstance(p, NDArray) else jnp.asarray(p)

    def t(vs):
        out = then_func(*_wrap_tree(vs, ctx))
        return _unwrap(out)

    def e(vs):
        out = else_func(*_wrap_tree(vs, ctx))
        return _unwrap(out)

    out = lax.cond(p_val.astype(bool).reshape(()), t, e, arrs)
    return jax.tree_util.tree_map(lambda a: _wrap(a, ctx), out)
