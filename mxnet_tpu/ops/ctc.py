"""CTC loss — log-domain forward algorithm via lax.scan.

Analog of the reference's src/operator/nn/ctc_loss.cc (warp-ctc /
cudnn CTC). TPU-native design: the alpha recursion runs as one
``lax.scan`` over time with the batch and label dimensions vectorized
on the VPU; blank label is index 0 (the reference's convention).
Gradients come free via autodiff of the scan (no hand-written backward
as in warp-ctc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _log_add(a, b):
    mx = jnp.maximum(a, b)
    safe = jnp.where(jnp.isfinite(mx), mx, 0.0)
    return jnp.where(
        (a <= NEG_INF / 2) & (b <= NEG_INF / 2), NEG_INF,
        safe + jnp.log(jnp.exp(a - safe) + jnp.exp(b - safe)))


def ctc_loss(logits, labels, input_lengths=None, label_lengths=None):
    """logits: (T, N, C) unnormalized; labels: (N, L) int (0 = blank is
    RESERVED; labels use 1..C-1 like the reference). Returns (N,) loss.
    """
    T, N, C = logits.shape
    L = labels.shape[1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    labels = labels.astype(jnp.int32)

    if input_lengths is None:
        input_lengths = jnp.full((N,), T, jnp.int32)
    else:
        input_lengths = input_lengths.astype(jnp.int32)
    if label_lengths is None:
        label_lengths = jnp.sum((labels > 0).astype(jnp.int32), axis=1)
    else:
        label_lengths = label_lengths.astype(jnp.int32)

    # extended label sequence with interleaved blanks: length S = 2L+1
    S = 2 * L + 1
    ext = jnp.zeros((N, S), jnp.int32)
    ext = ext.at[:, 1::2].set(labels)

    # allow skip transitions where ext[s] != ext[s-2] and not blank
    skip_ok = jnp.zeros((N, S), bool)
    skip_ok = skip_ok.at[:, 2:].set(
        (ext[:, 2:] != ext[:, :-2]) & (ext[:, 2:] != 0))

    batch_idx = jnp.arange(N)

    def emit(t):
        # log p of each extended symbol at time t: (N, S)
        return logp[t][batch_idx[:, None], ext]

    alpha0 = jnp.full((N, S), NEG_INF)
    alpha0 = alpha0.at[:, 0].set(emit(0)[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(label_lengths > 0,
                                           emit(0)[:, 1], NEG_INF))

    def step(alpha, t):
        shift1 = jnp.concatenate(
            [jnp.full((N, 1), NEG_INF), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate(
            [jnp.full((N, 2), NEG_INF), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(skip_ok, shift2, NEG_INF)
        new = _log_add(_log_add(alpha, shift1), shift2) + emit(t)
        # freeze batches whose input ended
        new = jnp.where((t < input_lengths)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))

    # total prob = alpha[last] + alpha[last-1] at position 2*label_len(-1)
    end = 2 * label_lengths
    last = alpha[batch_idx, end]
    second = jnp.where(label_lengths > 0,
                       alpha[batch_idx, jnp.maximum(end - 1, 0)], NEG_INF)
    return -_log_add(last, second)


def ctc_loss_nd(pred, label, pred_lengths=None, label_lengths=None):
    """NDArray-facing wrapper used by gluon.loss.CTCLoss — dispatches
    the REGISTERED ctc_loss op (one implementation, owned by the
    coverage gate)."""
    from ..ndarray.register import invoke, get_op
    from ..ndarray import full as _full

    if pred_lengths is None and label_lengths is not None:
        # the registered op takes lengths positionally (data first);
        # synthesize full-T data lengths so label_lengths can ride
        pred_lengths = _full((pred.shape[1],), pred.shape[0],
                             ctx=pred.ctx, dtype="int32")
    inputs = [pred, label]
    params = {"use_data_lengths": pred_lengths is not None,
              "use_label_lengths": label_lengths is not None}
    if pred_lengths is not None:
        inputs.append(pred_lengths)
    if label_lengths is not None:
        inputs.append(label_lengths)
    return invoke(get_op("ctc_loss"), inputs, params)
