"""TPU kernel & composite-op library.

Home of ops implemented beyond simple jnp/lax compositions: CTC
(ctc.py), Pallas fused kernels (pallas/), control-flow op wrappers
(control_flow.py). The op registry in ndarray/ exposes them to the
mx.nd / mx.sym namespaces.
"""
