"""Contrib vision operators: ROIAlign, BilinearResize2D,
AdaptiveAvgPooling2D, box_encode/box_decode.

TPU-native analogs of the reference's ``src/operator/contrib/
roi_align.{cc,cu}``, ``bilinear_resize.{cc,cu}``,
``adaptive_avg_pooling.{cc,cu}`` and ``bounding_box.cc``
(box_encode/box_decode) — the op tail the detection/segmentation model
families (Faster/Mask R-CNN, FCN) sit on. Each is a fixed-shape jax
computation (membership-mask reductions and gather-based bilinear
sampling instead of per-ROI dynamic loops) so everything jits, vmaps
and differentiates through XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.register import register_op

__all__ = []


def _bilinear_gather(img, ys, xs, zero_outside=False):
    """Bilinearly sample img (C, H, W) at float coords ys/xs (...,).
    ``zero_outside`` applies the reference ROIAlign boundary rule
    (roi_align.cc: samples with y < -1 or y > H contribute 0; in-band
    coords clamp to the edge pixels); without it coords just clamp
    (BilinearResize, whose grid is always in-range)."""
    c, h, w = img.shape
    if zero_outside:
        inside = ((ys >= -1.0) & (ys <= h) & (xs >= -1.0) & (xs <= w))
        ys = jnp.clip(ys, 0.0, h - 1)
        xs = jnp.clip(xs, 0.0, w - 1)
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1

    def at(y, x):
        yi = jnp.clip(y, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(x, 0, w - 1).astype(jnp.int32)
        return img[:, yi, xi]  # (C, ...)

    out = (at(y0, x0) * (wy0 * wx0) + at(y0, x0 + 1) * (wy0 * wx1)
           + at(y0 + 1, x0) * (wy1 * wx0) + at(y0 + 1, x0 + 1) * (wy1 * wx1))
    if zero_outside:
        out = out * inside
    return out


@register_op("_contrib_ROIAlign", aliases=("ROIAlign",))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """ROI align (reference src/operator/contrib/roi_align.cc): average
    of bilinear samples per bin — no coordinate quantization, fully
    differentiable through the sampling weights.

    ``sample_ratio <= 0`` means adaptive in the reference (ceil of the
    bin extent); static XLA shapes need a fixed grid, so it resolves to
    2 samples per bin axis (the detectron default). rois are
    ``[batch_idx, x1, y1, x2, y2]`` rows in image coordinates."""
    if position_sensitive:
        raise NotImplementedError(
            "ROIAlign(position_sensitive=True) (R-FCN PS-pooling: "
            "C/(ph*pw) channel groups) is not implemented — plain "
            "ROIAlign semantics would silently mis-train such a model")
    ph, pw = (int(p) for p in pooled_size)
    s = 2 if sample_ratio is None or int(sample_ratio) <= 0 \
        else int(sample_ratio)
    off = 0.5 if aligned else 0.0
    b = rois[:, 0].astype(jnp.int32)
    x1 = rois[:, 1] * spatial_scale - off
    y1 = rois[:, 2] * spatial_scale - off
    x2 = rois[:, 3] * spatial_scale - off
    y2 = rois[:, 4] * spatial_scale - off
    rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
    rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)

    # per-roi sample coordinates: (ph*s,) x (pw*s,)
    iy = (jnp.arange(ph * s) + 0.5) / s  # bin-fraction positions
    ix = (jnp.arange(pw * s) + 0.5) / s

    def one(img, yy1, xx1, hh, ww):
        ys = yy1 + iy * hh / ph
        xs = xx1 + ix * ww / pw
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")  # (ph*s, pw*s)
        samp = _bilinear_gather(img.astype(jnp.float32), gy, gx,
                                zero_outside=True)
        c = samp.shape[0]
        samp = samp.reshape(c, ph, s, pw, s)
        return samp.mean(axis=(2, 4))  # (C, ph, pw)

    out = jax.vmap(one)(data.astype(jnp.float32)[b], y1, x1, rh, rw)
    return out.astype(data.dtype)


@register_op("_contrib_BilinearResize2D", aliases=("BilinearResize2D",))
def bilinear_resize_2d(data, height=0, width=0, scale_height=None,
                       scale_width=None, mode="size"):
    """Bilinear resize of (N, C, H, W) (reference
    src/operator/contrib/bilinear_resize.cc — align-corners sampling:
    src = dst * (in-1)/(out-1), the cuDNN/caffe convention the
    reference uses, which differs from jax.image's half-pixel rule)."""
    n, c, h, w = data.shape
    if mode != "size":
        # odd_scale/like/to_even_* change the output-size computation;
        # running "size" math for them would be silently wrong shapes
        raise NotImplementedError(
            f"BilinearResize2D mode={mode!r}: only 'size' is implemented")
    # mode='size': explicit height/width win; scales are the fallback
    # when no explicit size is given (reference ignores scales when a
    # size is set)
    if int(height) <= 0 and scale_height is not None:
        height = int(round(h * float(scale_height)))
    if int(width) <= 0 and scale_width is not None:
        width = int(round(w * float(scale_width)))
    oh, ow = int(height), int(width)
    if oh <= 0 or ow <= 0:
        raise ValueError("BilinearResize2D needs height/width or scales")
    ys = jnp.arange(oh, dtype=jnp.float32) * \
        ((h - 1) / (oh - 1) if oh > 1 else 0.0)
    xs = jnp.arange(ow, dtype=jnp.float32) * \
        ((w - 1) / (ow - 1) if ow > 1 else 0.0)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    out = jax.vmap(lambda img: _bilinear_gather(img.astype(jnp.float32),
                                                gy, gx))(data)
    return out.astype(data.dtype)


@register_op("_contrib_AdaptiveAvgPooling2D",
             aliases=("AdaptiveAvgPooling2D",))
def adaptive_avg_pooling_2d(data, output_size=(1, 1)):
    """Adaptive average pooling (reference
    src/operator/contrib/adaptive_avg_pooling.cc): bin i covers
    [floor(i*H/oh), ceil((i+1)*H/oh)). Membership-mask matmuls give the
    whole op as two small contractions — one fused XLA program, exact
    gradients for free."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = (int(o) for o in output_size)
    n, c, h, w = data.shape

    def masks(nbins, size):
        i = jnp.arange(nbins, dtype=jnp.float32)[:, None]
        s = jnp.arange(size, dtype=jnp.float32)[None, :]
        lo = jnp.floor(i * size / nbins)
        hi = jnp.ceil((i + 1) * size / nbins)
        m = ((s >= lo) & (s < hi)).astype(jnp.float32)
        return m / jnp.maximum(m.sum(axis=1, keepdims=True), 1.0)

    mh = masks(oh, h)  # (oh, H), row-normalized
    mw = masks(ow, w)  # (ow, W)
    x = data.astype(jnp.float32)
    out = jnp.einsum("ph,nchw,qw->ncpq", mh, x, mw)
    return out.astype(data.dtype)


@register_op("_contrib_box_decode", aliases=("box_decode",))
def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):  # noqa: A002
    """Decode center-form offset predictions against anchors
    (reference bounding_box.cc BoxDecode; gluoncv NormalizedBoxCenterDecoder).
    data (B, N, 4) offsets; anchors (1, N, 4) in ``format``; returns
    corner boxes (B, N, 4)."""
    from .detection import _corner_to_center
    if format == "corner":
        ax, ay, aw, ah = _corner_to_center(anchors)
    elif format == "center":
        ax, ay, aw, ah = (anchors[..., i] for i in range(4))
    else:
        raise ValueError(
            f"box_decode: format must be 'corner' or 'center', "
            f"got {format!r}")
    cx = data[..., 0] * std0 * aw + ax
    cy = data[..., 1] * std1 * ah + ay
    tw = jnp.exp(data[..., 2] * std2)
    th = jnp.exp(data[..., 3] * std3)
    if clip is not None and clip > 0:
        tw = jnp.minimum(tw, clip)
        th = jnp.minimum(th, clip)
    w = tw * aw * 0.5
    h = th * ah * 0.5
    return jnp.stack([cx - w, cy - h, cx + w, cy + h], -1)


@register_op("_contrib_box_encode", aliases=("box_encode",),
             differentiable=False, num_visible_outputs=2)
def box_encode(samples, matches, anchors, refs,
               means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2)):
    """Encode matched ground-truth boxes into regression targets
    (reference bounding_box.cc BoxEncode). samples (B, N) with 1 for
    positive anchors; matches (B, N) GT indices; anchors (B, N, 4) and
    refs (B, M, 4) corner boxes. Returns (targets (B, N, 4),
    masks (B, N, 4)) — masks zero out non-positive anchors."""
    means = jnp.asarray(means, jnp.float32)
    stds = jnp.asarray(stds, jnp.float32)

    from .detection import _corner_to_center

    def one(smp, mat, anc, ref):
        g = ref[jnp.maximum(mat, 0).astype(jnp.int32)]  # (N, 4)
        ax, ay, aw, ah = _corner_to_center(anc)
        gx, gy, gw, gh = _corner_to_center(g)
        eps = 1e-8
        t = jnp.stack([
            (gx - ax) / jnp.maximum(aw, eps),
            (gy - ay) / jnp.maximum(ah, eps),
            jnp.log(jnp.maximum(gw, eps) / jnp.maximum(aw, eps)),
            jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ah, eps))], -1)
        t = (t - means) / stds
        m = (smp > 0.5).astype(jnp.float32)[:, None] * jnp.ones((1, 4))
        return t * m, m

    return jax.vmap(one)(samples, matches, anchors, refs)
