"""Contrib vision operators: ROIAlign, BilinearResize2D,
AdaptiveAvgPooling2D, box_encode/box_decode.

TPU-native analogs of the reference's ``src/operator/contrib/
roi_align.{cc,cu}``, ``bilinear_resize.{cc,cu}``,
``adaptive_avg_pooling.{cc,cu}`` and ``bounding_box.cc``
(box_encode/box_decode) — the op tail the detection/segmentation model
families (Faster/Mask R-CNN, FCN) sit on. Each is a fixed-shape jax
computation (membership-mask reductions and gather-based bilinear
sampling instead of per-ROI dynamic loops) so everything jits, vmaps
and differentiates through XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.register import register_op

__all__ = []


def _bilinear_gather(img, ys, xs, boundary="clamp"):
    """Bilinearly sample img (C, H, W) at float coords ys/xs (...,).

    boundary modes (the two references disagree at the border band):
    - "clamp" (default): coords clamp to the edge — BilinearResize,
      whose grid is always in-range anyway.
    - "zero_band": roi_align.cc rule — samples with y < -1 or y > H
      contribute 0, in-band coords clamp to the edge pixels at full
      weight.
    - "fade": deformable_im2col rule — each of the 4 corner taps
      contributes only if it lies inside the image, so values fade
      linearly to 0 across the border (conv zero-padding semantics).
    """
    c, h, w = img.shape
    if boundary == "zero_band":
        inside = ((ys >= -1.0) & (ys <= h) & (xs >= -1.0) & (xs <= w))
        ys = jnp.clip(ys, 0.0, h - 1)
        xs = jnp.clip(xs, 0.0, w - 1)
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1

    def at(y, x):
        yi = jnp.clip(y, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(x, 0, w - 1).astype(jnp.int32)
        v = img[:, yi, xi]  # (C, ...)
        if boundary == "fade":
            ok = ((y >= 0) & (y <= h - 1) & (x >= 0) & (x <= w - 1))
            v = v * ok
        return v

    out = (at(y0, x0) * (wy0 * wx0) + at(y0, x0 + 1) * (wy0 * wx1)
           + at(y0 + 1, x0) * (wy1 * wx0) + at(y0 + 1, x0 + 1) * (wy1 * wx1))
    if boundary == "zero_band":
        out = out * inside
    return out


@register_op("_contrib_ROIAlign", aliases=("ROIAlign",))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """ROI align (reference src/operator/contrib/roi_align.cc): average
    of bilinear samples per bin — no coordinate quantization, fully
    differentiable through the sampling weights.

    ``sample_ratio <= 0`` means adaptive in the reference (ceil of the
    bin extent); static XLA shapes need a fixed grid, so it resolves to
    2 samples per bin axis (the detectron default). rois are
    ``[batch_idx, x1, y1, x2, y2]`` rows in image coordinates."""
    if position_sensitive:
        raise NotImplementedError(
            "ROIAlign(position_sensitive=True) (R-FCN PS-pooling: "
            "C/(ph*pw) channel groups) is not implemented — plain "
            "ROIAlign semantics would silently mis-train such a model")
    ph, pw = (int(p) for p in pooled_size)
    s = 2 if sample_ratio is None or int(sample_ratio) <= 0 \
        else int(sample_ratio)
    off = 0.5 if aligned else 0.0
    b = rois[:, 0].astype(jnp.int32)
    x1 = rois[:, 1] * spatial_scale - off
    y1 = rois[:, 2] * spatial_scale - off
    x2 = rois[:, 3] * spatial_scale - off
    y2 = rois[:, 4] * spatial_scale - off
    rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
    rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)

    # per-roi sample coordinates: (ph*s,) x (pw*s,)
    iy = (jnp.arange(ph * s) + 0.5) / s  # bin-fraction positions
    ix = (jnp.arange(pw * s) + 0.5) / s

    def one(img, yy1, xx1, hh, ww):
        ys = yy1 + iy * hh / ph
        xs = xx1 + ix * ww / pw
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")  # (ph*s, pw*s)
        samp = _bilinear_gather(img.astype(jnp.float32), gy, gx,
                                boundary="zero_band")
        c = samp.shape[0]
        samp = samp.reshape(c, ph, s, pw, s)
        return samp.mean(axis=(2, 4))  # (C, ph, pw)

    out = jax.vmap(one)(data.astype(jnp.float32)[b], y1, x1, rh, rw)
    return out.astype(data.dtype)


@register_op("_contrib_BilinearResize2D", aliases=("BilinearResize2D",))
def bilinear_resize_2d(data, height=0, width=0, scale_height=None,
                       scale_width=None, mode="size"):
    """Bilinear resize of (N, C, H, W) (reference
    src/operator/contrib/bilinear_resize.cc — align-corners sampling:
    src = dst * (in-1)/(out-1), the cuDNN/caffe convention the
    reference uses, which differs from jax.image's half-pixel rule)."""
    n, c, h, w = data.shape
    if mode != "size":
        # odd_scale/like/to_even_* change the output-size computation;
        # running "size" math for them would be silently wrong shapes
        raise NotImplementedError(
            f"BilinearResize2D mode={mode!r}: only 'size' is implemented")
    # mode='size': explicit height/width win; scales are the fallback
    # when no explicit size is given (reference ignores scales when a
    # size is set)
    if int(height) <= 0 and scale_height is not None:
        height = int(round(h * float(scale_height)))
    if int(width) <= 0 and scale_width is not None:
        width = int(round(w * float(scale_width)))
    oh, ow = int(height), int(width)
    if oh <= 0 or ow <= 0:
        raise ValueError("BilinearResize2D needs height/width or scales")
    ys = jnp.arange(oh, dtype=jnp.float32) * \
        ((h - 1) / (oh - 1) if oh > 1 else 0.0)
    xs = jnp.arange(ow, dtype=jnp.float32) * \
        ((w - 1) / (ow - 1) if ow > 1 else 0.0)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    out = jax.vmap(lambda img: _bilinear_gather(img.astype(jnp.float32),
                                                gy, gx))(data)
    return out.astype(data.dtype)


@register_op("_contrib_AdaptiveAvgPooling2D",
             aliases=("AdaptiveAvgPooling2D",))
def adaptive_avg_pooling_2d(data, output_size=(1, 1)):
    """Adaptive average pooling (reference
    src/operator/contrib/adaptive_avg_pooling.cc): bin i covers
    [floor(i*H/oh), ceil((i+1)*H/oh)). Membership-mask matmuls give the
    whole op as two small contractions — one fused XLA program, exact
    gradients for free."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = (int(o) for o in output_size)
    n, c, h, w = data.shape

    def masks(nbins, size):
        # INTEGER bin boundaries: float floor/ceil of i*size/nbins is
        # not exact on TPU f32 (ceil(4.0000005) = 5 pulls a stray row
        # into the bin); integer floor/ceil division is exact
        i = jnp.arange(nbins, dtype=jnp.int32)[:, None]
        s = jnp.arange(size, dtype=jnp.int32)[None, :]
        lo = (i * size) // nbins
        hi = ((i + 1) * size + nbins - 1) // nbins
        m = ((s >= lo) & (s < hi)).astype(jnp.float32)
        return m / jnp.maximum(m.sum(axis=1, keepdims=True), 1.0)

    mh = masks(oh, h)  # (oh, H), row-normalized
    mw = masks(ow, w)  # (ow, W)
    x = data.astype(jnp.float32)
    out = jnp.einsum("ph,nchw,qw->ncpq", mh, x, mw)
    return out.astype(data.dtype)


@register_op("_contrib_box_decode", aliases=("box_decode",))
def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner"):  # noqa: A002
    """Decode center-form offset predictions against anchors
    (reference bounding_box.cc BoxDecode; gluoncv NormalizedBoxCenterDecoder).
    data (B, N, 4) offsets; anchors (1, N, 4) in ``format``; returns
    corner boxes (B, N, 4)."""
    from .detection import _corner_to_center
    if format == "corner":
        ax, ay, aw, ah = _corner_to_center(anchors)
    elif format == "center":
        ax, ay, aw, ah = (anchors[..., i] for i in range(4))
    else:
        raise ValueError(
            f"box_decode: format must be 'corner' or 'center', "
            f"got {format!r}")
    cx = data[..., 0] * std0 * aw + ax
    cy = data[..., 1] * std1 * ah + ay
    tw = jnp.exp(data[..., 2] * std2)
    th = jnp.exp(data[..., 3] * std3)
    if clip is not None and clip > 0:
        tw = jnp.minimum(tw, clip)
        th = jnp.minimum(th, clip)
    w = tw * aw * 0.5
    h = th * ah * 0.5
    return jnp.stack([cx - w, cy - h, cx + w, cy + h], -1)


@register_op("_contrib_box_encode", aliases=("box_encode",),
             differentiable=False, num_visible_outputs=2)
def box_encode(samples, matches, anchors, refs,
               means=(0.0, 0.0, 0.0, 0.0), stds=(0.1, 0.1, 0.2, 0.2)):
    """Encode matched ground-truth boxes into regression targets
    (reference bounding_box.cc BoxEncode). samples (B, N) with 1 for
    positive anchors; matches (B, N) GT indices; anchors (B, N, 4) and
    refs (B, M, 4) corner boxes. Returns (targets (B, N, 4),
    masks (B, N, 4)) — masks zero out non-positive anchors."""
    means = jnp.asarray(means, jnp.float32)
    stds = jnp.asarray(stds, jnp.float32)

    from .detection import _corner_to_center

    def one(smp, mat, anc, ref):
        g = ref[jnp.maximum(mat, 0).astype(jnp.int32)]  # (N, 4)
        ax, ay, aw, ah = _corner_to_center(anc)
        gx, gy, gw, gh = _corner_to_center(g)
        eps = 1e-8
        t = jnp.stack([
            (gx - ax) / jnp.maximum(aw, eps),
            (gy - ay) / jnp.maximum(ah, eps),
            jnp.log(jnp.maximum(gw, eps) / jnp.maximum(aw, eps)),
            jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ah, eps))], -1)
        t = (t - means) / stds
        m = (smp > 0.5).astype(jnp.float32)[:, None] * jnp.ones((1, 4))
        return t * m, m

    return jax.vmap(one)(samples, matches, anchors, refs)


@register_op("_contrib_DeformableConvolution",
             aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=0, num_group=1, num_deformable_group=1,
                           no_bias=False):
    """Deformable convolution v1 (reference
    src/operator/contrib/deformable_convolution.cc): each kernel tap
    samples the input at its regular grid position plus a learned
    per-location 2D offset, bilinearly interpolated, then the taps
    contract with the weights as an ordinary convolution.

    TPU-first: deformable im2col is a gather per tap (K*K bilinear
    sample maps, fully vectorized) followed by ONE einsum contraction
    — the MXU does the heavy lifting; the reference's custom CUDA
    im2col kernels become jax gathers. data (B, C, H, W); offset
    (B, 2*KK*num_deformable_group, OH, OW) with channel order
    [g0k0_y, g0k0_x, g0k1_y, ...]; weight (O, C/num_group, kh, kw).
    Everything differentiates (data, offset AND weight) through XLA.
    """
    kh, kw = (int(k) for k in kernel)
    sh, sw = (int(s) for s in stride)
    dh, dw = (int(d) for d in dilate)
    ph, pw = (int(p) for p in pad)
    b, c, h, w = data.shape
    o = int(num_filter) if num_filter else weight.shape[0]
    kk = kh * kw
    g = int(num_group)
    dg = int(num_deformable_group)
    if c % g or o % g:
        raise ValueError("channels must divide num_group")
    if c % dg:
        raise ValueError("channels must divide num_deformable_group")
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    if offset.shape[1] != 2 * kk * dg:
        raise ValueError(
            f"offset needs {2 * kk * dg} channels, got {offset.shape[1]}")

    base_y = jnp.arange(oh, dtype=jnp.float32) * sh - ph   # (OH,)
    base_x = jnp.arange(ow, dtype=jnp.float32) * sw - pw   # (OW,)
    cg = c // dg  # data channels per deformable group

    def sample_one(img, off):
        # img (C, H, W), off (2*KK*dg, OH, OW) -> cols (C, KK, OH, OW)
        taps = []
        for idx in range(kk):
            i, j = idx // kw, idx % kw
            groups = []
            for gi in range(dg):
                dy = off[(gi * kk + idx) * 2]       # (OH, OW)
                dx = off[(gi * kk + idx) * 2 + 1]
                ys = base_y[:, None] + i * dh + dy
                xs = base_x[None, :] + j * dw + dx
                part = _bilinear_gather(img[gi * cg:(gi + 1) * cg],
                                        ys, xs, boundary="fade")
                groups.append(part)                 # (cg, OH, OW)
            taps.append(jnp.concatenate(groups, axis=0))
        return jnp.stack(taps, axis=1)              # (C, KK, OH, OW)

    cols = jax.vmap(sample_one)(data.astype(jnp.float32),
                                offset.astype(jnp.float32))
    wr = weight.astype(jnp.float32).reshape(g, o // g, c // g, kk)
    colsg = cols.reshape(b, g, c // g, kk, oh, ow)
    out = jnp.einsum("bgckyx,gock->bgoyx", colsg, wr)
    out = out.reshape(b, o, oh, ow)
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.float32)[None, :, None, None]
    return out.astype(data.dtype)


@register_op("_contrib_PSROIPooling", aliases=("PSROIPooling",))
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=0,
                  pooled_size=7, group_size=0):
    """Position-sensitive ROI pooling (reference
    src/operator/contrib/psroi_pooling.cc, R-FCN): input channels are
    laid out as (output_dim * group^2); bin (i, j) of the output
    average-pools the spatial cells of channel group (i*group + j).
    rois are ``[batch_idx, x1, y1, x2, y2]`` image-coordinate rows."""
    k = int(pooled_size)
    gs = int(group_size) if group_size else k
    od = int(output_dim)
    b, c, h, w = data.shape
    if od * gs * gs != c:
        raise ValueError(
            f"PSROIPooling: channels {c} != output_dim*group^2 "
            f"({od}*{gs}^2)")
    bb = rois[:, 0].astype(jnp.int32)
    # C round() semantics (half away from zero; coords are
    # non-negative) — jnp.round is banker's rounding and disagrees at
    # *.5 (reference psroi_pooling.cc uses round())
    _round_c = lambda v: jnp.floor(v + 0.5)
    x1 = _round_c(rois[:, 1]) * spatial_scale
    y1 = _round_c(rois[:, 2]) * spatial_scale
    x2 = _round_c(rois[:, 3] + 1.0) * spatial_scale
    y2 = _round_c(rois[:, 4] + 1.0) * spatial_scale
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)

    def one(img, yy1, xx1, hh, ww):
        # img (C, H, W) -> (od, k, k)
        # bin membership masks over the roi's spatial extent
        i = jnp.arange(k, dtype=jnp.float32)
        s_y = jnp.arange(h, dtype=jnp.float32)[None, :]
        s_x = jnp.arange(w, dtype=jnp.float32)[None, :]
        lo_y = jnp.floor(yy1 + i[:, None] * hh / k)
        hi_y = jnp.ceil(yy1 + (i[:, None] + 1) * hh / k)
        lo_x = jnp.floor(xx1 + i[:, None] * ww / k)
        hi_x = jnp.ceil(xx1 + (i[:, None] + 1) * ww / k)
        my = ((s_y >= jnp.clip(lo_y, 0, h)) & (s_y < jnp.clip(hi_y, 0, h)))
        mx_ = ((s_x >= jnp.clip(lo_x, 0, w)) & (s_x < jnp.clip(hi_x, 0, w)))
        my = my.astype(jnp.float32)     # (k, H)
        mx_ = mx_.astype(jnp.float32)   # (k, W)
        # bin (i, j) pools channel group (floor(i*gs/k), floor(j*gs/k))
        # — reference psroi_pooling.cc supports group_size != pooled_size
        imgg = img.reshape(od, gs, gs, h, w)
        gidx = (jnp.arange(k) * gs // k).astype(jnp.int32)
        sel = imgg[:, gidx[:, None], gidx[None, :]]  # (od, k, k, h, w)
        sums = jnp.einsum("ih,dijhw,jw->dij", my, sel, mx_)
        area = jnp.einsum("ih,jw->ij", my, mx_)
        out = sums / jnp.maximum(area, 1.0)[None]
        return out

    out = jax.vmap(one)(data.astype(jnp.float32)[bb], y1, x1, rh, rw)
    return out.astype(data.dtype)
