"""SSD-family detection operators (anchors, target matching, NMS).

TPU-native analog of the reference's
``src/operator/contrib/multibox_prior.{cc,cu}``,
``multibox_target.{cc,cu}``, ``multibox_detection.{cc,cu}`` and
``bounding_box.cc`` (box_iou / box_nms / bipartite_matching). The
reference hand-rolls CUDA kernels with dynamic worklists; here every
op is a fixed-shape jax computation (sort + masked ``lax.fori_loop``
suppression instead of dynamic queues) so the whole family jits and
vmaps over the batch — suppressed entries are marked ``-1`` in place,
matching the reference's output contract exactly.

All ops are non-differentiable (the reference registers no gradient:
target generation and NMS backward are zeros).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.register import register_op

__all__ = []


# ---------------------------------------------------------------------------
# box format helpers
# ---------------------------------------------------------------------------
def _corner_to_center(b):
    w = b[..., 2] - b[..., 0]
    h = b[..., 3] - b[..., 1]
    return (b[..., 0] + w * 0.5, b[..., 1] + h * 0.5, w, h)


def _iou_corner(a, b, eps=1e-12):
    """IoU of two corner-format box sets: a (..., N, 4) vs b (..., M, 4)
    -> (..., N, M)."""
    ax1, ay1, ax2, ay2 = (a[..., :, None, i] for i in range(4))
    bx1, by1, bx2, by2 = (b[..., None, :, i] for i in range(4))
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0.0) * jnp.maximum(ay2 - ay1, 0.0)
    area_b = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
    return inter / (area_a + area_b - inter + eps)


def _to_corner(b, in_format):
    if in_format == "center":
        x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
        return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], -1)
    return b


def _from_corner(b, out_format):
    if out_format == "center":
        x1, y1, x2, y2 = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
        return jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], -1)
    return b


# ---------------------------------------------------------------------------
# box_iou / bipartite matching
# ---------------------------------------------------------------------------
@register_op("_contrib_box_iou", aliases=("box_iou",), differentiable=False)
def box_iou(lhs, rhs, format="corner"):  # noqa: A002
    lhs = _to_corner(lhs, format)
    rhs = _to_corner(rhs, format)
    return _iou_corner(lhs, rhs)


@register_op("_contrib_bipartite_matching", aliases=("bipartite_matching",),
             differentiable=False, num_visible_outputs=2)
def bipartite_matching(dist, is_ascend=False, threshold=None, topk=-1):
    """Greedy bipartite matching on a pairwise score matrix
    (reference bounding_box.cc BipartiteMatching): repeatedly take the
    globally best (row, col) pair, mark both used. Returns
    (row_match, col_match): for each row the matched col (or -1), and
    for each col the matched row (or -1). Batched input (..., N, M) is
    matched independently per leading index (gluoncv matchers rely on
    this reference behavior)."""
    d = dist
    if d.ndim < 2:
        raise ValueError("bipartite_matching expects a >=2-D dist matrix")
    if d.ndim > 2:
        lead = d.shape[:-2]
        flat = d.reshape((-1,) + d.shape[-2:])
        rows, cols = jax.vmap(
            lambda x: bipartite_matching(x, is_ascend=is_ascend,
                                         threshold=threshold, topk=topk))(flat)
        return (rows.reshape(lead + rows.shape[-1:]),
                cols.reshape(lead + cols.shape[-1:]))
    n, m = d.shape
    k = min(n, m) if topk is None or topk < 0 else min(topk, min(n, m))
    big = jnp.asarray(jnp.inf, d.dtype)
    sign = 1.0 if not is_ascend else -1.0
    # sign-flip FIRST, then mask NaN — masking before the flip would
    # turn NaN into +inf under is_ascend and greedily match it
    score0 = jnp.where(jnp.isnan(d), -big, d * sign)  # maximize always

    def body(i, carry):
        score, row_m, col_m = carry
        flat = jnp.argmax(score)
        r, c = flat // m, flat % m
        best = score[r, c]
        dval = best * sign  # back to the caller's scale
        ok = best > -big
        if threshold is not None:
            ok = jnp.logical_and(
                ok, dval <= threshold if is_ascend else dval >= threshold)
        row_m = jnp.where(ok, row_m.at[r].set(c.astype(jnp.int32)), row_m)
        col_m = jnp.where(ok, col_m.at[c].set(r.astype(jnp.int32)), col_m)
        score = jnp.where(ok, score.at[r, :].set(-big).at[:, c].set(-big),
                          score)
        return score, row_m, col_m

    row_m = jnp.full((n,), -1, jnp.int32)
    col_m = jnp.full((m,), -1, jnp.int32)
    _, row_m, col_m = jax.lax.fori_loop(0, k, body, (score0, row_m, col_m))
    return row_m.astype(d.dtype), col_m.astype(d.dtype)


# ---------------------------------------------------------------------------
# box_nms
# ---------------------------------------------------------------------------
def _nms_single(boxes, scores, ids, valid, overlap_thresh, force_suppress):
    """Greedy NMS over one row set. boxes corner (N,4); returns keep
    mask (N,) bool, iterating highest-score-first (fixed N steps)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    cid = ids[order]
    v = valid[order]
    iou = _iou_corner(b, b)
    same = jnp.logical_or(force_suppress, cid[:, None] == cid[None, :])
    sup_pair = jnp.logical_and(iou > overlap_thresh, same)

    def body(i, keep):
        # i suppresses later j when i itself is kept
        row = jnp.logical_and(sup_pair[i], jnp.arange(n) > i)
        row = jnp.logical_and(row, keep[i])
        return jnp.logical_and(keep, jnp.logical_not(row))

    keep_sorted = jax.lax.fori_loop(0, n, body, v)
    keep = jnp.zeros((n,), bool).at[order].set(keep_sorted)
    return keep


@register_op("_contrib_box_nms", aliases=("box_nms",), differentiable=False)
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Suppressed entries become all -1 rows; survivors are sorted by
    score descending (reference bounding_box.cc contract)."""
    squeeze = data.ndim == 2
    d = data[None] if squeeze else data
    batch = d.shape[:-2]
    d2 = d.reshape((-1,) + d.shape[-2:])

    def one(rows):
        scores = rows[:, score_index]
        boxes = _to_corner(rows[:, coord_start:coord_start + 4], in_format)
        ids = rows[:, id_index] if id_index >= 0 else jnp.zeros(rows.shape[0])
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid = jnp.logical_and(valid, ids != background_id)
        if topk is not None and topk > 0:
            # rank among VALID rows only (reference filters by
            # valid_thresh/background before applying topk)
            rank = jnp.argsort(jnp.argsort(
                -jnp.where(valid, scores, -jnp.inf)))
            valid = jnp.logical_and(valid, rank < topk)
        keep = _nms_single(boxes, scores, ids, valid, overlap_thresh,
                           bool(force_suppress))
        out = jnp.where(keep[:, None], rows, -jnp.ones_like(rows))
        # survivors first, by score desc (suppressed rows sink)
        order = jnp.argsort(-jnp.where(keep, scores, -jnp.inf))
        out = out[order]
        if out_format != in_format:
            coords = out[:, coord_start:coord_start + 4]
            conv = _from_corner(_to_corner(coords, in_format), out_format)
            out = out.at[:, coord_start:coord_start + 4].set(
                jnp.where(keep[order][:, None], conv, -1.0))
        return out

    res = jax.vmap(one)(d2).reshape(d.shape)
    return res[0] if squeeze else res.reshape(batch + d.shape[-2:])


# ---------------------------------------------------------------------------
# MultiBoxPrior
# ---------------------------------------------------------------------------
@register_op("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",),
             differentiable=False)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes for a (B, C, H, W) feature map, corner format in
    [0, 1]: per cell, sizes[k] x ratios[0] for all k plus sizes[0] x
    ratios[j] for j > 0 (reference multibox_prior-inl.h ordering)."""
    sizes = tuple(float(s) for s in (sizes if isinstance(sizes, (tuple, list))
                                     else (sizes,)))
    ratios = tuple(float(r) for r in (ratios if isinstance(ratios, (tuple, list))
                                      else (ratios,)))
    h, w = data.shape[-2], data.shape[-1]
    # steps/offsets are (y, x) per the reference param convention
    step_y = 1.0 / h if steps[0] < 0 else steps[0]
    step_x = 1.0 / w if steps[1] < 0 else steps[1]
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    # reference multibox_prior.cc scales the half-WIDTH by the feature
    # map aspect (in_height/in_width) so a `size` means the same image
    # fraction on both axes of a non-square map; half-height is unscaled
    aspect = float(h) / float(w)
    half = []
    for k, s in enumerate(sizes):
        half.append((s * aspect * (ratios[0] ** 0.5) / 2.0,
                     s / (ratios[0] ** 0.5) / 2.0))
    for r in ratios[1:]:
        half.append((sizes[0] * aspect * (r ** 0.5) / 2.0,
                     sizes[0] / (r ** 0.5) / 2.0))
    hw = jnp.asarray([p[0] for p in half], jnp.float32)  # (A,)
    hh = jnp.asarray([p[1] for p in half], jnp.float32)
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")  # (H, W)
    gx = gx[..., None]
    gy = gy[..., None]
    anchors = jnp.stack([gx - hw, gy - hh, gx + hw, gy + hh], -1)  # (H,W,A,4)
    anchors = anchors.reshape(1, -1, 4)
    if clip:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return anchors


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------
@register_op("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",),
             differentiable=False, num_visible_outputs=3)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Match anchors to ground truth and emit training targets
    (reference multibox_target-inl.h):

    - anchor (1, N, 4) corner, label (B, M, 5) rows [cls x1 y1 x2 y2]
      (cls = -1 pads), cls_pred (B, num_cls+1, N) for negative mining.
    - returns loc_target (B, N*4) variance-encoded offsets, loc_mask
      (B, N*4) 1 where matched, cls_target (B, N) with class+1 for
      matched, 0 background, ignore_label for mined-out negatives.

    Matching follows the reference: greedy bipartite pass gives every
    GT its best anchor, then any unmatched anchor takes its best GT if
    IoU >= overlap_threshold. Negative mining keeps the
    ``negative_mining_ratio``x hardest negatives by background score
    deficit among anchors whose best IoU < negative_mining_thresh.
    """
    anchors = anchor.reshape(-1, 4)
    n = anchors.shape[0]
    var = jnp.asarray(variances, jnp.float32)
    acx, acy, aw, ah = _corner_to_center(anchors)

    def one(lab, cpred):
        m = lab.shape[0]
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _iou_corner(anchors, gt_boxes)  # (N, M)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)

        # pass 1: greedy bipartite — each valid GT claims its best anchor
        big = jnp.asarray(jnp.inf, iou.dtype)
        match = jnp.full((n,), -1, jnp.int32)

        def bip(i, carry):
            score, match = carry
            flat = jnp.argmax(score)
            r, c = flat // m, flat % m
            ok = score[r, c] > 0.0
            match = jnp.where(ok, match.at[r].set(c.astype(jnp.int32)), match)
            score = jnp.where(ok, score.at[r, :].set(-big).at[:, c].set(-big),
                              score)
            return score, match

        _, match = jax.lax.fori_loop(0, m, bip, (iou, match))

        # pass 2: threshold matching for still-unmatched anchors
        best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
        best_iou = jnp.max(iou, axis=1)
        thr_ok = jnp.logical_and(match < 0, best_iou >= overlap_threshold)
        match = jnp.where(thr_ok, best_gt, match)
        matched = match >= 0
        midx = jnp.maximum(match, 0)

        # classification target (class ids shift +1; 0 = background)
        cls_t = jnp.where(matched, lab[midx, 0] + 1.0, 0.0)

        # negative mining on background anchors
        if negative_mining_ratio > 0:
            num_pos = matched.sum()
            max_neg = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                jnp.asarray(int(minimum_negative_samples), jnp.int32))
            neg_cand = jnp.logical_and(~matched,
                                       best_iou < negative_mining_thresh)
            # hardness: best non-background score minus background score
            bg = cpred[0]
            fg = jnp.max(cpred[1:], axis=0)
            hardness = jnp.where(neg_cand, fg - bg, -jnp.inf)
            rank = jnp.argsort(jnp.argsort(-hardness))
            keep_neg = jnp.logical_and(neg_cand, rank < max_neg)
            cls_t = jnp.where(jnp.logical_and(~matched, ~keep_neg),
                              jnp.asarray(float(ignore_label)), cls_t)

        # localization target: variance-encoded center-form offsets
        gcx, gcy, gw, gh = _corner_to_center(gt_boxes[midx])
        eps = 1e-8
        tx = (gcx - acx) / jnp.maximum(aw, eps) / var[0]
        ty = (gcy - acy) / jnp.maximum(ah, eps) / var[1]
        tw = jnp.log(jnp.maximum(gw, eps) / jnp.maximum(aw, eps)) / var[2]
        th = jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ah, eps)) / var[3]
        loc_t = jnp.stack([tx, ty, tw, th], -1)
        loc_t = jnp.where(matched[:, None], loc_t, 0.0).reshape(-1)
        loc_m = jnp.where(matched[:, None],
                          jnp.ones((n, 4), jnp.float32), 0.0).reshape(-1)
        return loc_t, loc_m, cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
    return loc_t, loc_m, cls_t


# ---------------------------------------------------------------------------
# MultiBoxDetection
# ---------------------------------------------------------------------------
@register_op("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",),
             differentiable=False)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode predictions into detections (B, N, 6) rows
    [class_id, score, x1, y1, x2, y2]; pruned/suppressed rows are -1
    (reference multibox_detection-inl.h)."""
    anchors = anchor.reshape(-1, 4)
    n = anchors.shape[0]
    var = jnp.asarray(variances, jnp.float32)
    acx, acy, aw, ah = _corner_to_center(anchors)

    def one(cp, lp):
        # cp (num_cls+1, N), lp (N*4,)
        off = lp.reshape(n, 4)
        cx = off[:, 0] * var[0] * aw + acx
        cy = off[:, 1] * var[1] * ah + acy
        w = jnp.exp(off[:, 2] * var[2]) * aw
        h = jnp.exp(off[:, 3] * var[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor
        fg = jnp.concatenate([cp[:background_id], cp[background_id + 1:]], 0)
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        valid = score > threshold
        if nms_topk is not None and nms_topk > 0:
            # reference keeps the top-k candidates BEFORE suppression —
            # discarded ranks can neither survive nor suppress others
            rank = jnp.argsort(jnp.argsort(
                -jnp.where(valid, score, -jnp.inf)))
            valid = jnp.logical_and(valid, rank < nms_topk)
        rows = jnp.concatenate(
            [cls_id[:, None], score[:, None], boxes], -1)
        keep = _nms_single(boxes, jnp.where(valid, score, -jnp.inf),
                           cls_id, valid, nms_threshold, bool(force_suppress))
        out = jnp.where(keep[:, None], rows, -jnp.ones_like(rows))
        order = jnp.argsort(-jnp.where(keep, score, -jnp.inf))
        return out[order]

    return jax.vmap(one)(cls_prob, loc_pred)
