"""Flash attention Pallas kernel (fwd + bwd, causal, O(S) memory).

Reference analog: upstream MXNet has NO fused attention op (SURVEY
§5.7) — BERT-era attention is composed from batch_dot+softmax
(src/operator/tensor/dot-inl.h + nn/softmax.cc), materializing the
(S, S) score matrix in HBM. This kernel is the TPU-first replacement:
blockwise online-softmax with the query block resident in VMEM, scores
never leaving the chip.

Also exports ``flash_attention_with_lse`` returning the per-row
log-sum-exp — the combiner state blockwise/ring schemes need. Note:
parallel/ring_attention.py currently folds chunks with a pure-jnp
online-softmax (differentiable through lax.scan) rather than this
forward-only kernel; this entry point serves external combiners and
golden tests.

Shapes: q (B, H, Sq, D), k/v (B, H, Skv, D). ``q_offset`` is the
global position of q row 0 relative to k row 0 (ring attention passes
the rotating chunk offset; 0 for vanilla causal). The same
Sq != Skv + offset geometry is what the decode engine's CHUNKED
PREFILL steps (serving/decode_model.py ``prefill_chunk``) produce —
a small q block at global position ``start`` attending to the paged
KV written so far. That path runs the composed jnp attention over
gathered cache pages today (small Sq keeps the score block trivially
VMEM-resident), but the masking convention is deliberately identical
(``col <= q_offset + row``) so the chunk loop can be pointed at this
kernel without changing results.

Variable-length batches ARE handled natively: ``kv_lens`` (B,) int32
gives each example's valid key/value length. The per-example length
rides in SMEM; score columns at or beyond it are masked in both the
forward and the fused backward, and (q, k) tiles that start past the
length are SKIPPED entirely (no MXU work — short rows in a padded
batch cost proportionally less). Rows whose query position is padding
produce zeros through the l==0 guard; with the loss masking padded
positions (cotangent zero there), their dk/dv contributions vanish
identically, so gradients match the composed masked softmax exactly.

Arbitrary ADDITIVE masks (relative-position biases etc.) are not
expressible as lengths — the op layer falls back to the jnp composed
path for those.

SEQUENCE PACKING is handled natively too: ``segment_ids`` (B, S) int32
gives each token's segment (sequence) id within its packed row
(io/packing.py emits them; 0 marks padding slots). Attention is
block-diagonal — a (q, k) pair contributes only when the two tokens
share a segment id — so multiple short sequences ride one row with
exactly zero cross-sequence attention, forward and backward. The ids
ride in VMEM in the lane/sublane-broadcast layout Mosaic compares
cheaply (q ids replicated across 128 lanes, kv ids across 8 sublanes —
the jax.experimental flash reference's SegmentIds idiom), and a
per-block id-range summary (min/max per q/k tile) rides in SMEM so a
(q-block, kv-block) pair whose id ranges are disjoint is SKIPPED
whole (no MXU work) — sound for arbitrary ids since disjoint ranges
cannot share a value, and tight when the packer lays segments out
contiguously (monotonic ids). Combine with ``kv_lens`` (the packed
row's used length) so tail padding is masked and padding rows emit
exact zeros through the l==0 guard; packed outputs and gradients then
match each sequence run unpacked, bit-for-bit in block-free cases and
within fp tolerance otherwise. Packing requires Sq == Skv (self
attention; the KV-cache decode path has no packed analog here).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._util import resolve_interpret, x32

_NEG_INF = -1e30
# segment-id VMEM layout (the jax flash reference's SegmentIds idiom):
# q ids broadcast across the 128 lanes, kv ids across 8 sublanes, so the
# (block_q, block_k) equality mask is a repeat + a sublane-broadcast
# compare — both native Mosaic moves, no transposes
_SEG_LANES = 128
_SEG_SUBLANES = 8
# tile-padding sentinels: q pad rows and kv pad cols must never match
# each other (or any real id ≥ 0), so they get DISTINCT negatives
_SEG_PAD_Q = -2
_SEG_PAD_KV = -3


def _dot_precision(dtype):
    """Explicit per-dot precision: Mosaic rejects the process-wide
    'high' matmul precision that __init__.py sets for f32 numerics
    parity. Kernel blocks are f32-cast copies of the caller's data, so
    for bf16 models a DEFAULT (single-pass bf16) dot is lossless; true
    f32 inputs get HIGHEST (exact f32 via MXU passes)."""
    return (lax.Precision.HIGHEST if jnp.dtype(dtype) == jnp.float32
            else lax.Precision.DEFAULT)


def _segment_mask(qseg_ref, kseg_ref, block_k):
    """(block_q, block_k) same-segment mask from the broadcast-layout id
    tiles: q ids (block_q, 128) repeated across lane groups, kv ids one
    sublane row (1, block_k) broadcast down the sublanes."""
    qs = qseg_ref[0]
    if block_k > _SEG_LANES:
        qs = pltpu.repeat(qs, block_k // _SEG_LANES, axis=1)
    elif block_k < _SEG_LANES:  # never hit: block_k is a 128-multiple
        qs = qs[:, :block_k]
    return qs == kseg_ref[0][:1, :]


def _seg_range(qrng_ref, krng_ref, i, j, n_heads):
    """The (i, j) pair's segment-id range summaries (4 SMEM scalars) —
    None refs mean no segment masking."""
    if qrng_ref is None:
        return None
    b = pl.program_id(0) // np.int32(n_heads)
    return (qrng_ref[0, b, i], qrng_ref[1, b, i],
            krng_ref[0, b, j], krng_ref[1, b, j])


def _pair_mask(i, j, causal, q_offset, kv_len, block_q, block_k,
               kvl=None, smask=None):
    """Validity mask for the (i, j) score block, or None when every
    position is statically visible (no kv padding, not causal, no
    per-example length, no segments) — the common dense shape skips the
    iota/where entirely. ``kvl`` is the traced per-example valid kv
    length (SMEM scalar); it subsumes the static tail-pad mask since
    kvl <= kv_len. ``smask`` is the precomputed (block_q, block_k)
    same-segment mask (packing)."""
    mask = smask
    if kvl is not None:
        col = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        lm = col < kvl
        mask = lm if mask is None else jnp.logical_and(mask, lm)
    elif kv_len % block_k != 0:  # padded tail block exists
        col = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        lm = col < kv_len
        mask = lm if mask is None else jnp.logical_and(mask, lm)
    if causal:
        col = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        row = i * block_q + q_offset + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cm = col <= row
        mask = cm if mask is None else jnp.logical_and(mask, cm)
    return mask


def _block_visible(i, j, causal, q_offset, block_q, block_k, kvl,
                   segrng=None):
    """Whether the (i, j) tile has ANY live score: causal skip, the
    per-example length skip (tiles starting at/after kvl are dead —
    the variable-length fast path's whole-tile saving), and the packed
    segment-range skip (disjoint id ranges cannot share a segment, so
    cross-sequence tiles cost no MXU work)."""
    q_last = (i + 1) * block_q - 1 + q_offset
    vis = jnp.logical_or(not causal, j * block_k <= q_last)
    if kvl is not None:
        vis = jnp.logical_and(vis, j * block_k < kvl)
    if segrng is not None:
        qmin, qmax, kmin, kmax = segrng
        vis = jnp.logical_and(vis, jnp.logical_and(qmin <= kmax,
                                                   kmin <= qmax))
    return vis


def _fwd_kernel(q_ref, k_ref, v_ref, kvl_ref, *rest,
                sm_scale, causal, q_offset, kv_len, block_q, block_k,
                precision, dynamic_kv, dynamic_seg, n_heads):
    if dynamic_seg:
        (qseg_ref, kseg_ref, qrng_ref, krng_ref,
         o_ref, lse_ref, acc_sc, m_sc, l_sc) = rest
    else:
        qseg_ref = kseg_ref = qrng_ref = krng_ref = None
        o_ref, lse_ref, acc_sc, m_sc, l_sc = rest
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    kvl = kvl_ref[pl.program_id(0)] if dynamic_kv else None

    @pl.when(j == 0)
    def _():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    # skip: causal invisibility, a tile past the example's kv length,
    # or a packed tile whose segment-id ranges are disjoint
    visible = _block_visible(i, j, causal, q_offset, block_q, block_k, kvl,
                             _seg_range(qrng_ref, krng_ref, i, j, n_heads))

    @pl.when(visible)
    def _():
        # q arrives pre-scaled by sm_scale (host side) so no per-pair
        # (block_q, block_k) elementwise scale runs on the VPU
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)

        smask = _segment_mask(qseg_ref, kseg_ref, block_k) \
            if dynamic_seg else None
        mask = _pair_mask(i, j, causal, q_offset, kv_len, block_q, block_k,
                          kvl, smask)
        if mask is not None:
            s = jnp.where(mask, s, np.float32(_NEG_INF))

        m_prev = m_sc[:]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        # rows with no visible key yet keep m_cur at the -1e30 sentinel;
        # exp(s - m_cur) would be exp(0)=1 there, polluting l/acc with an
        # average of V. Force p (and alpha) to 0 until a real score lands.
        seen = m_cur > np.float32(_NEG_INF / 2)
        alpha = jnp.where(seen, alpha, np.float32(0.0))
        p = jnp.where(seen, jnp.exp(s - m_cur), np.float32(0.0))
        l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        m_sc[:] = m_cur

    @pl.when(j == nk - 1)
    def _():
        l = l_sc[:]
        l_safe = jnp.where(l == np.float32(0.0), np.float32(1.0), l)
        o_ref[0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == np.float32(0.0), np.float32(_NEG_INF),
                        m_sc[:] + jnp.log(l_safe))
        lse_ref[0] = lse


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   kvl_ref, *rest,
                   sm_scale, causal, q_offset, kv_len, block_q, block_k,
                   precision, dynamic_kv, dynamic_seg, n_heads):
    if dynamic_seg:
        qseg_ref, kseg_ref, qrng_ref, krng_ref, dq_ref, dq_sc = rest
    else:
        qseg_ref = kseg_ref = qrng_ref = krng_ref = None
        dq_ref, dq_sc = rest
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    kvl = kvl_ref[pl.program_id(0)] if dynamic_kv else None

    @pl.when(j == 0)
    def _():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    visible = _block_visible(i, j, causal, q_offset, block_q, block_k, kvl,
                             _seg_range(qrng_ref, krng_ref, i, j, n_heads))

    @pl.when(visible)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        smask = _segment_mask(qseg_ref, kseg_ref, block_k) \
            if dynamic_seg else None
        mask = _pair_mask(i, j, causal, q_offset, kv_len, block_q, block_k,
                          kvl, smask)
        p = jnp.exp(s - lse) if mask is None \
            else jnp.where(mask, jnp.exp(s - lse), np.float32(0.0))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        ds = p * (dp - delta)
        dq_sc[:] = dq_sc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)

    @pl.when(j == nk - 1)
    def _():
        # dq is wrt the ORIGINAL q: rescale once on the small (bq, d)
        # block (q was pre-scaled; ds here is wrt unscaled scores)
        dq_ref[0] = (dq_sc[:] * np.float32(sm_scale)).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    kvl_ref, *rest,
                    sm_scale, causal, q_offset, kv_len, block_q, block_k,
                    precision, dynamic_kv, dynamic_seg, n_heads):
    # grid: (BH, nk, nq) — q is the inner (sequential) axis
    if dynamic_seg:
        (qseg_ref, kseg_ref, qrng_ref, krng_ref,
         dk_ref, dv_ref, dk_sc, dv_sc) = rest
    else:
        qseg_ref = kseg_ref = qrng_ref = krng_ref = None
        dk_ref, dv_ref, dk_sc, dv_sc = rest
    j, i = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    kvl = kvl_ref[pl.program_id(0)] if dynamic_kv else None

    @pl.when(i == 0)
    def _():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    visible = _block_visible(i, j, causal, q_offset, block_q, block_k, kvl,
                             _seg_range(qrng_ref, krng_ref, i, j, n_heads))

    @pl.when(visible)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        smask = _segment_mask(qseg_ref, kseg_ref, block_k) \
            if dynamic_seg else None
        mask = _pair_mask(i, j, causal, q_offset, kv_len, block_q, block_k,
                          kvl, smask)
        p = jnp.exp(s - lse) if mask is None \
            else jnp.where(mask, jnp.exp(s - lse), np.float32(0.0))

        dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        ds = p * (dp - delta)
        dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      kvl_ref, *rest,
                      sm_scale, causal, q_offset, kv_len, block_q, block_k,
                      precision, dynamic_kv, dynamic_seg, n_heads):
    """One-pass backward: dq, dk, dv from a SINGLE traversal of the
    (q block, k block) grid — the score matrix s and dp are computed
    once per pair instead of once in a dq kernel and again in a dkv
    kernel (VERDICT r2 #2: 7 block-matmuls per pair drop to 5, and
    q/do/lse/delta stream through VMEM once, not twice).

    Grid (BH, nk, nq): k outer so dk/dv accumulate in VMEM scratch;
    each (j, i) step owns a distinct dq partial block (no output
    revisiting, so no read-modify-write hazard with Pallas's input
    prefetch pipeline) and the per-k-block partials are summed by XLA
    outside the kernel.
    """
    if dynamic_seg:
        (qseg_ref, kseg_ref, qrng_ref, krng_ref,
         dq_ref, dk_ref, dv_ref, dk_sc, dv_sc) = rest
    else:
        qseg_ref = kseg_ref = qrng_ref = krng_ref = None
        dq_ref, dk_ref, dv_ref, dk_sc, dv_sc = rest
    j, i = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    kvl = kvl_ref[pl.program_id(0)] if dynamic_kv else None

    @pl.when(i == 0)
    def _():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    visible = _block_visible(i, j, causal, q_offset, block_q, block_k, kvl,
                             _seg_range(qrng_ref, krng_ref, i, j, n_heads))

    @pl.when(visible)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        smask = _segment_mask(qseg_ref, kseg_ref, block_k) \
            if dynamic_seg else None
        mask = _pair_mask(i, j, causal, q_offset, kv_len, block_q, block_k,
                          kvl, smask)
        p = jnp.exp(s - lse) if mask is None \
            else jnp.where(mask, jnp.exp(s - lse), np.float32(0.0))

        dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        ds = p * (dp - delta)
        dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        dq_ref[0, 0] = (jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision) * np.float32(sm_scale)).astype(dq_ref.dtype)

    @pl.when(jnp.logical_not(visible))
    def _():
        # skipped pair (causal or past-kv-length): this step still owns
        # its dq partial block — zero it (output buffers start
        # uninitialized)
        dq_ref[0, 0] = jnp.zeros_like(dq_ref[0, 0])

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _pad_len(s, block):
    return ((s + block - 1) // block) * block


def _pad0(x, pad):
    """jnp.pad with a fill constant pinned to x's dtype: a bare python
    0 is weakly typed, and mixing the x32 trace region with an x64
    caller jit makes two differently-typed lowerings of jnp.pad's
    private helper collide on some jax versions (symbolic-executor
    graphs trace these pads under x64)."""
    return jnp.pad(x, pad, constant_values=np.zeros((), x.dtype))


def _pick_blocks(sq, skv):
    # v5e-measured defaults (BASELINE.md round-3/4 sweeps): 512-wide q
    # tiles with the k tile as large as fits (cap 2048) — at seq2048
    # the single-k-block grid (512x2048) measured 87.6k tok/s vs 74.8k
    # at 512x512 (+17%): k/v stay resident, the fused backward needs no
    # dq partial-sum, and (q, do, lse, delta) reloads amortize across
    # the whole row. VMEM: the f32 score block is bq*bk*4 = 4 MB at
    # 512x2048 (d<=128 keeps operand blocks ~1 MB), inside the ~16 MB
    # budget. Override per run with MXNET_TPU_FLASH_BLOCK_Q/K.
    from ... import envvars
    bq_cap = envvars.get("MXNET_TPU_FLASH_BLOCK_Q")
    bk_cap = envvars.get("MXNET_TPU_FLASH_BLOCK_K")
    bq = min(bq_cap, _pad_len(sq, 8))
    bk = min(bk_cap, _pad_len(skv, 128))
    return bq, bk


def _expand_kv_lens(kv_lens, b, h):
    """(B,) per-example lengths -> (B*H,) int32 whole-array SMEM
    operand (kernels index it by program_id(0); Mosaic requires either
    tile-aligned blocks or the full array, so the full tiny vector it
    is)."""
    return jnp.broadcast_to(
        kv_lens.astype(jnp.int32).reshape(b, 1), (b, h)).reshape(b * h)


def _prep_segments(segment_ids, b, sq, skv, sq_p, skv_p, block_q, block_k):
    """Host-side packed-attention operands from (B, S) segment ids:

    - qseg (B, sq_p, 128): ids broadcast across lanes (q side);
    - kseg (B, 8, skv_p): ids broadcast across sublanes (kv side);
    - qrng (2, B, nq) / krng (2, B, nk): per-tile id min/max (SMEM)
      driving the whole-block disjoint-range skip.

    Tile padding uses distinct negative sentinels per side so padded q
    rows can never match padded kv cols. Arrays are per-BATCH (not
    per-head); kernels index them with program_id(0) // n_heads."""
    seg = segment_ids.astype(jnp.int32)
    qseg = seg if sq_p == sq else jnp.pad(
        seg, ((0, 0), (0, sq_p - sq)),
        constant_values=np.int32(_SEG_PAD_Q))
    kseg = seg if skv_p == skv else jnp.pad(
        seg, ((0, 0), (0, skv_p - skv)),
        constant_values=np.int32(_SEG_PAD_KV))
    nq, nk = sq_p // block_q, skv_p // block_k
    qt = qseg.reshape(b, nq, block_q)
    kt = kseg.reshape(b, nk, block_k)
    qrng = jnp.stack([qt.min(-1), qt.max(-1)])
    krng = jnp.stack([kt.min(-1), kt.max(-1)])
    qseg = lax.broadcast_in_dim(qseg, (b, sq_p, _SEG_LANES), (0, 1))
    kseg = lax.broadcast_in_dim(kseg, (b, _SEG_SUBLANES, skv_p), (0, 2))
    return qseg, kseg, qrng, krng


def _seg_specs(block_q, block_k, n_heads, transposed_grid):
    """BlockSpecs for the four segment operands. ``transposed_grid``:
    the dkv/fused backward runs (BH, nk, nq), the fwd/dq grids run
    (BH, nq, nk) — the index maps pick the right program axes."""
    h32 = np.int32(n_heads)  # i32 divisor: index maps must stay i32
    if transposed_grid:
        qmap = lambda b_, j, i: (b_ // h32, i, 0)  # noqa: E731
        kmap = lambda b_, j, i: (b_ // h32, 0, j)  # noqa: E731
    else:
        qmap = lambda b_, i, j: (b_ // h32, i, 0)  # noqa: E731
        kmap = lambda b_, i, j: (b_ // h32, 0, j)  # noqa: E731
    return [
        pl.BlockSpec((1, block_q, _SEG_LANES), qmap,
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, _SEG_SUBLANES, block_k), kmap,
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]


@x32
def _flash_fwd(q, k, v, sm_scale, causal, q_offset, interpret,
               block_q=None, block_k=None, kv_lens=None,
               segment_ids=None):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    if segment_ids is not None and sq != skv:
        raise ValueError(
            f"segment_ids (packing) requires self-attention shapes, got "
            f"sq={sq} != skv={skv}")
    bq0, bk0 = _pick_blocks(sq, skv)
    block_q = block_q or bq0
    block_k = block_k or bk0
    sq_p, skv_p = _pad_len(sq, block_q), _pad_len(skv, block_k)

    # pre-scale q so the kernels never run the (block_q, block_k)
    # elementwise *sm_scale (dq is rescaled on its small output block)
    qf = (q * sm_scale).astype(q.dtype).reshape(b * h, sq, d)
    kf = k.reshape(b * h, skv, d)
    vf = v.reshape(b * h, skv, d)
    if sq_p != sq:
        qf = _pad0(qf, ((0, 0), (0, sq_p - sq), (0, 0)))
    if skv_p != skv:
        kf = _pad0(kf, ((0, 0), (0, skv_p - skv), (0, 0)))
        vf = _pad0(vf, ((0, 0), (0, skv_p - skv), (0, 0)))

    bh = b * h
    dynamic_kv = kv_lens is not None
    dynamic_seg = segment_ids is not None
    kvlf = _expand_kv_lens(kv_lens, b, h) if dynamic_kv \
        else jnp.full((bh,), skv, jnp.int32)
    nq, nk = sq_p // block_q, skv_p // block_k
    kern = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        q_offset=q_offset, kv_len=skv, block_q=block_q, block_k=block_k,
        precision=_dot_precision(q.dtype), dynamic_kv=dynamic_kv,
        dynamic_seg=dynamic_seg, n_heads=h)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    operands = [qf, kf, vf, kvlf]
    if dynamic_seg:
        in_specs += _seg_specs(block_q, block_k, h, transposed_grid=False)
        operands += list(_prep_segments(segment_ids, b, sq, skv,
                                        sq_p, skv_p, block_q, block_k))
    o, lse = pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    o = o[:, :sq].reshape(b, h, sq, d)
    lse = lse[:, :sq, 0].reshape(b, h, sq)
    return o, lse


@x32
def _flash_bwd(q, k, v, o, lse, do, sm_scale, causal, q_offset, interpret,
               block_q=None, block_k=None, dlse=None, kv_lens=None,
               segment_ids=None):
    from ... import envvars as _envvars

    b, h, sq, d = q.shape
    skv = k.shape[2]
    bq0, bk0 = _pick_blocks(sq, skv)
    block_q = block_q or bq0
    block_k = block_k or bk0
    sq_p, skv_p = _pad_len(sq, block_q), _pad_len(skv, block_k)
    bh = b * h
    dynamic_kv = kv_lens is not None
    kvlf = _expand_kv_lens(kv_lens, b, h) if dynamic_kv \
        else jnp.full((bh,), skv, jnp.int32)
    seg_ops = None if segment_ids is None else list(
        _prep_segments(segment_ids, b, sq, skv, sq_p, skv_p,
                       block_q, block_k))

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(bh, sq, 1)
    if dlse is not None:
        # d lse/d s = p, so the lse cotangent enters ds = p*(dp - delta)
        # as delta_eff = delta - dlse (one extra subtract, no new kernel)
        delta = delta - dlse.astype(jnp.float32).reshape(bh, sq, 1)
    # pre-scaled q (matches forward): s = q'k^T directly; dk = ds^T q'
    # IS the original-k gradient, dq rescales by sm_scale at the write
    qf = (q * sm_scale).astype(q.dtype).reshape(bh, sq, d)
    kf = k.reshape(bh, skv, d)
    vf = v.reshape(bh, skv, d)
    dof = do.reshape(bh, sq, d)
    lsef = lse.reshape(bh, sq, 1)
    if sq_p != sq:
        pad = ((0, 0), (0, sq_p - sq), (0, 0))
        qf, dof = _pad0(qf, pad), _pad0(dof, pad)
        # padded q rows: lse=-inf would give exp(s - -inf)=inf; use +inf
        # so p=exp(-inf)=0 for those rows
        lsef = jnp.pad(lsef, ((0, 0), (0, sq_p - sq), (0, 0)),
                       constant_values=np.float32(np.inf))
        delta = _pad0(delta, ((0, 0), (0, sq_p - sq), (0, 0)))
    if skv_p != skv:
        pad = ((0, 0), (0, skv_p - skv), (0, 0))
        kf, vf = _pad0(kf, pad), _pad0(vf, pad)

    nq, nk = sq_p // block_q, skv_p // block_k
    common = dict(sm_scale=sm_scale, causal=causal, q_offset=q_offset,
                  kv_len=skv, block_q=block_q, block_k=block_k,
                  precision=_dot_precision(q.dtype), dynamic_kv=dynamic_kv,
                  dynamic_seg=seg_ops is not None, n_heads=h)

    # the fused pass writes nk f32 dq-partial copies to HBM; past nk=2
    # that memory/write cliff outweighs the recompute saving, so long
    # multi-k-block rows (S > 2*block_k cap) take the split path whose
    # dq accumulates in VMEM scratch
    if nk <= 2 and not _envvars.get("MXNET_TPU_FLASH_SPLIT_BWD"):
        return _flash_bwd_fused(qf, kf, vf, dof, lsef, delta, kvlf, seg_ops,
                                (b, h, sq, skv, d), nq, nk, common,
                                interpret, k.dtype, v.dtype, q.dtype)
    return _flash_bwd_split(qf, kf, vf, dof, lsef, delta, kvlf, seg_ops,
                            (b, h, sq, skv, d), nq, nk, common,
                            interpret, k.dtype, v.dtype, q.dtype)


def _flash_bwd_fused(qf, kf, vf, dof, lsef, delta, kvlf, seg_ops, dims,
                     nq, nk, common, interpret, k_dtype, v_dtype, q_dtype):
    """Single-pass dq/dk/dv (default; MXNET_TPU_FLASH_SPLIT_BWD=1
    selects the two-kernel path for A/B and as a fallback)."""
    b, h, sq, skv, d = dims
    bh = b * h
    block_q, block_k = common["block_q"], common["block_k"]
    sq_p, skv_p = nq * block_q, nk * block_k

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b_, j, i: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, d), lambda b_, j, i: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1), lambda b_, j, i: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1), lambda b_, j, i: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    operands = [qf, kf, vf, dof, lsef, delta, kvlf]
    if seg_ops is not None:
        in_specs += _seg_specs(block_q, block_k, h, transposed_grid=True)
        operands += seg_ops
    dq_part, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, **common),
        grid=(bh, nk, nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, j, i: (b_, j, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            # f32 partials: the cross-k-block sum happens outside the
            # kernel in f32, then casts once to the caller dtype
            jax.ShapeDtypeStruct((bh, nk, sq_p, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, skv_p, d), k_dtype),
            jax.ShapeDtypeStruct((bh, skv_p, d), v_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    dq = dq_part.sum(axis=1).astype(q_dtype) if nk > 1 \
        else dq_part[:, 0].astype(q_dtype)
    dq = dq[:, :sq].reshape(b, h, sq, d)
    dk = dk[:, :skv].reshape(b, h, skv, d)
    dv = dv[:, :skv].reshape(b, h, skv, d)
    return dq, dk, dv


def _flash_bwd_split(qf, kf, vf, dof, lsef, delta, kvlf, seg_ops, dims,
                     nq, nk, common, interpret, k_dtype, v_dtype, q_dtype):
    b, h, sq, skv, d = dims
    bh = b * h
    block_q, block_k = common["block_q"], common["block_k"]
    sq_p, skv_p = nq * block_q, nk * block_k

    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    operands = [qf, kf, vf, dof, lsef, delta, kvlf]
    if seg_ops is not None:
        dq_specs += _seg_specs(block_q, block_k, h, transposed_grid=False)
        operands += seg_ops
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(bh, nq, nk),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, sq_p, d), q_dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*operands)

    dkv_specs = [
        pl.BlockSpec((1, block_q, d), lambda b_, j, i: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, d), lambda b_, j, i: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1), lambda b_, j, i: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, 1), lambda b_, j, i: (b_, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    if seg_ops is not None:
        dkv_specs += _seg_specs(block_q, block_k, h, transposed_grid=True)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(bh, nk, nq),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, skv_p, d), k_dtype),
            jax.ShapeDtypeStruct((bh, skv_p, d), v_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    dq = dq[:, :sq].reshape(b, h, sq, d)
    dk = dk[:, :skv].reshape(b, h, skv, d)
    dv = dv[:, :skv].reshape(b, h, skv, d)
    return dq, dk, dv


def _int_ct(x):
    """Cotangent for an integer tensor argument (kv_lens, segment_ids):
    None when absent, float0 zeros when present (custom_vjp contract
    for int primals)."""
    if x is None:
        return None
    return np.zeros(x.shape, jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, sm_scale=None, causal=False,
                             q_offset=0, interpret=None, kv_lens=None,
                             segment_ids=None):
    """Flash attention returning (out, lse) — DIFFERENTIABLE in both
    outputs (the lse cotangent folds into the backward's delta term).

    lse has shape (B, H, Sq), fp32 — the combiner state blockwise/ring
    schemes need; ring_attention folds per-chunk (out, lse) pairs with
    the log-sum-exp combiner and lets gradients flow through both.
    ``kv_lens`` (B,) int32 masks keys at/after each example's length.
    ``segment_ids`` (B, S) int32 restricts attention to same-segment
    pairs (sequence packing; see the module docstring).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash_fwd(q, k, v, sm_scale, bool(causal), int(q_offset),
                      resolve_interpret(interpret), kv_lens=kv_lens,
                      segment_ids=segment_ids)


def _flash_lse_vjp_fwd(q, k, v, sm_scale, causal, q_offset, interpret,
                       kv_lens=None, segment_ids=None):
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    o, lse = _flash_fwd(q, k, v, sm_scale, bool(causal), int(q_offset),
                        resolve_interpret(interpret), kv_lens=kv_lens,
                        segment_ids=segment_ids)
    return (o, lse), (q, k, v, o, lse, kv_lens, segment_ids)


def _flash_lse_vjp_bwd(sm_scale, causal, q_offset, interpret, res, cts):
    q, k, v, o, lse, kv_lens, segment_ids = res
    do, dlse = cts
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, sm_scale, bool(causal),
                            int(q_offset), resolve_interpret(interpret),
                            dlse=dlse, kv_lens=kv_lens,
                            segment_ids=segment_ids)
    return dq, dk, dv, _int_ct(kv_lens), _int_ct(segment_ids)


flash_attention_with_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, sm_scale=None, causal=False, q_offset=0,
                    interpret=None, kv_lens=None, segment_ids=None):
    """softmax(q k^T * scale [+causal/length/segment mask]) v,
    blockwise in VMEM. ``kv_lens`` (B,) int32 masks keys at/after each
    example's valid length (variable-length batches, e.g. BERT
    padding); ``segment_ids`` (B, S) int32 makes attention
    block-diagonal over packed sequences (see module docstring)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    o, _ = _flash_fwd(q, k, v, sm_scale, bool(causal), int(q_offset),
                      resolve_interpret(interpret), kv_lens=kv_lens,
                      segment_ids=segment_ids)
    return o


def _flash_vjp_fwd(q, k, v, sm_scale, causal, q_offset, interpret,
                   kv_lens=None, segment_ids=None):
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    o, lse = _flash_fwd(q, k, v, sm_scale, bool(causal), int(q_offset),
                        resolve_interpret(interpret), kv_lens=kv_lens,
                        segment_ids=segment_ids)
    return o, (q, k, v, o, lse, kv_lens, segment_ids)


def _flash_vjp_bwd(sm_scale, causal, q_offset, interpret, res, do):
    q, k, v, o, lse, kv_lens, segment_ids = res
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, sm_scale, bool(causal),
                            int(q_offset), resolve_interpret(interpret),
                            kv_lens=kv_lens, segment_ids=segment_ids)
    return dq, dk, dv, _int_ct(kv_lens), _int_ct(segment_ids)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# -- paged KV decode path ---------------------------------------------------
# The packed path above requires Sq == Skv (self attention over one
# packed row). Autoregressive DECODE is the opposite shape: one (or a
# small chunk of) query token(s) per sequence against a long per-
# sequence KV history that lives in a PAGED pool (serving/kvcache.py —
# the vLLM layout: fixed-size pages, per-sequence page tables). This
# kernel lifts the restriction for that case: K/V are read THROUGH the
# page table — the table rides as a scalar-prefetch operand so each
# (batch row, head, logical page) grid step DMAs exactly the physical
# page it needs — with per-row ``kv_len`` masking and a whole-page
# skip for table slots at/after each row's length. Forward-only by
# design (decode is inference; the training path keeps the packed
# kernel above).

def _paged_fwd_kernel(tbl_ref, kvl_ref, q_ref, k_ref, v_ref, o_ref,
                      acc_sc, m_sc, l_sc, *, sq, page_size, block_q,
                      precision):
    b, j = pl.program_id(0), pl.program_id(2)
    npages = pl.num_programs(2)
    kvl = kvl_ref[b]

    @pl.when(j == 0)
    def _():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    # whole-page skip: a table slot at/after ceil(kvl / page_size) holds
    # padding (or a recycled page) — no MXU work, no pollution
    @pl.when(j * page_size < kvl)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)          # (block_q, d)
        k = k_ref[0, 0].astype(jnp.float32)          # (page_size, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        col = j * page_size + lax.broadcasted_iota(
            jnp.int32, (block_q, page_size), 1)
        row = lax.broadcasted_iota(jnp.int32, (block_q, page_size), 0)
        # q chunk row i sits at global position kvl - sq + i (the chunk
        # is the TAIL of the sequence, already written to the pages):
        # causal decode masks cols past that position; col < kvl also
        # bounds q pad rows (block_q >= sq) to written history only
        mask = jnp.logical_and(col <= kvl - np.int32(sq) + row,
                               col < kvl)
        s = jnp.where(mask, s, np.float32(_NEG_INF))
        m_prev = m_sc[:]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        seen = m_cur > np.float32(_NEG_INF / 2)
        alpha = jnp.where(seen, jnp.exp(m_prev - m_cur), np.float32(0.0))
        p = jnp.where(seen, jnp.exp(s - m_cur), np.float32(0.0))
        l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        m_sc[:] = m_cur

    @pl.when(j == npages - 1)
    def _():
        l = l_sc[:]
        l_safe = jnp.where(l == np.float32(0.0), np.float32(1.0), l)
        o_ref[0, 0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)


@x32
def paged_flash_attention(q, k_pages, v_pages, page_table, kv_lens,
                          sm_scale=None, interpret=None):
    """Decode-path flash attention over a paged KV pool.

    Shapes::

        q          (B, H, Sq, D)   the last Sq tokens of each sequence
                                   (Sq=1 steady-state decode; small Sq
                                   for chunked prefill)
        k_pages    (P, H, page_size, D)   the pool (all sequences)
        v_pages    (P, H, page_size, D)
        page_table (B, NP) int32   per-row physical page ids, padded
                                   with any in-range id past the row's
                                   ceil(kv_len / page_size) pages
        kv_lens    (B,) int32      per-row written history length,
                                   INCLUDING the Sq query tokens

    K/V are gathered through the page table inside the kernel (the
    table is a scalar-prefetch operand driving the page DMA index
    map); columns at/after each row's ``kv_len`` are masked and whole
    dead pages are skipped. Causal within the chunk: q row ``i`` sees
    positions ``<= kv_len - Sq + i``. Rows whose ``kv_len`` is 0 emit
    exact zeros. Forward-only (inference); differentiation is
    unsupported by design.
    """
    b, h, sq, d = q.shape
    p_, hk, page_size, dk = k_pages.shape
    if (hk, dk) != (h, d) or v_pages.shape != k_pages.shape:
        raise ValueError(
            f"page pool shape {k_pages.shape}/{v_pages.shape} does not "
            f"match q heads/dim ({h}, {d})")
    if page_table.ndim != 2 or page_table.shape[0] != b:
        raise ValueError(
            f"page_table must be (B={b}, NP), got {page_table.shape}")
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    npages = page_table.shape[1]
    block_q = _pad_len(sq, 8)
    qf = (q * sm_scale).astype(q.dtype)
    if block_q != sq:
        qf = _pad0(qf, ((0, 0), (0, 0), (0, block_q - sq), (0, 0)))
    kern = functools.partial(
        _paged_fwd_kernel, sq=sq, page_size=page_size, block_q=block_q,
        precision=_dot_precision(q.dtype))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, npages),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, j, tbl, kvl: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda b_, h_, j, tbl, kvl: (tbl[b_, j], h_,
                                                      0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda b_, h_, j, tbl, kvl: (tbl[b_, j], h_,
                                                      0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d),
            lambda b_, h_, j, tbl, kvl: (b_, h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ])
    out = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, block_q, d), q.dtype),
        interpret=resolve_interpret(interpret),
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32),
      qf, k_pages, v_pages)
    return out[:, :, :sq]


def paged_attention_reference(q, k_pages, v_pages, page_table, kv_lens,
                              sm_scale=None):
    """Dense jnp reference for :func:`paged_flash_attention` — the
    golden the kernel tests compare against, and the CPU fallback the
    decode model uses off-TPU. Gathers the table'd pages, masks
    columns past each row's ``kv_len`` (causal within the Sq chunk)
    and runs a plain max-subtracted softmax. Every row's computation
    is independent of the others — the property the join/leave
    solo-parity golden leans on."""
    b, h, sq, d = q.shape
    page_size = k_pages.shape[2]
    npages = page_table.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    # (B, NP, H, page, D) -> (B, H, NP*page, D)
    k = jnp.moveaxis(k_pages[page_table], 2, 1) \
        .reshape(b, h, npages * page_size, d)
    v = jnp.moveaxis(v_pages[page_table], 2, 1) \
        .reshape(b, h, npages * page_size, d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * sm_scale,
                   k.astype(jnp.float32))
    col = jnp.arange(npages * page_size, dtype=jnp.int32)
    row = jnp.arange(sq, dtype=jnp.int32)
    kvl = kv_lens.astype(jnp.int32)[:, None, None, None]
    mask = jnp.logical_and(
        col[None, None, None, :]
        <= kvl - np.int32(sq) + row[None, None, :, None],
        col[None, None, None, :] < kvl)
    s = jnp.where(mask, s, np.float32(_NEG_INF))
    m = jnp.max(s, axis=-1, keepdims=True)
    seen = m > np.float32(_NEG_INF / 2)
    p = jnp.where(seen, jnp.exp(s - m), np.float32(0.0))
    l = jnp.sum(p, axis=-1, keepdims=True)
    l = jnp.where(l == 0.0, np.float32(1.0), l)
    return (jnp.einsum("bhqk,bhkd->bhqd", p / l,
                       v.astype(jnp.float32))).astype(q.dtype)
