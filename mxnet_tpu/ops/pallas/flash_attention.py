"""Flash attention Pallas kernel (fwd + bwd, causal, O(S) memory).

Reference analog: upstream MXNet has NO fused attention op (SURVEY
§5.7) — BERT-era attention is composed from batch_dot+softmax
(src/operator/tensor/dot-inl.h + nn/softmax.cc), materializing the
(S, S) score matrix in HBM. This kernel is the TPU-first replacement:
blockwise online-softmax with the query block resident in VMEM, scores
never leaving the chip.

Also exports ``flash_attention_with_lse`` returning the per-row
log-sum-exp — the combiner state blockwise/ring schemes need. Note:
parallel/ring_attention.py currently folds chunks with a pure-jnp
online-softmax (differentiable through lax.scan) rather than this
forward-only kernel; this entry point serves external combiners and
golden tests.

Shapes: q (B, H, Sq, D), k/v (B, H, Skv, D). ``q_offset`` is the
global position of q row 0 relative to k row 0 (ring attention passes
the rotating chunk offset; 0 for vanilla causal).

Variable-length / arbitrary additive masks are NOT handled here — the
op layer falls back to the jnp path when a mask tensor is supplied.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._util import resolve_interpret, x32

_NEG_INF = -1e30


def _dot_precision(dtype):
    """Explicit per-dot precision: Mosaic rejects the process-wide
    'high' matmul precision that __init__.py sets for f32 numerics
    parity. Kernel blocks are f32-cast copies of the caller's data, so
    for bf16 models a DEFAULT (single-pass bf16) dot is lossless; true
    f32 inputs get HIGHEST (exact f32 via MXU passes)."""
    return (lax.Precision.HIGHEST if jnp.dtype(dtype) == jnp.float32
            else lax.Precision.DEFAULT)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_sc, m_sc, l_sc, *,
                sm_scale, causal, q_offset, kv_len, block_q, block_k,
                precision):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    # causal skip: block is visible iff its first k column can be seen
    # by the last q row of this block
    q_last = (i + 1) * block_q - 1 + q_offset
    visible = jnp.logical_or(not causal, j * block_k <= q_last)

    @pl.when(visible)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision) * sm_scale

        col = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = i * block_q + q_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, col <= row)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_sc[:]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        # rows with no visible key yet keep m_cur at the -1e30 sentinel;
        # exp(s - m_cur) would be exp(0)=1 there, polluting l/acc with an
        # average of V. Force p (and alpha) to 0 until a real score lands.
        seen = m_cur > _NEG_INF / 2
        alpha = jnp.where(seen, alpha, 0.0)
        p = jnp.where(seen, jnp.exp(s - m_cur), 0.0)
        l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        m_sc[:] = m_cur

    @pl.when(j == nk - 1)
    def _():
        l = l_sc[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, _NEG_INF, m_sc[:] + jnp.log(l_safe))
        lse_ref[0] = lse


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_sc, *,
                   sm_scale, causal, q_offset, kv_len, block_q, block_k,
                   precision):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    q_last = (i + 1) * block_q - 1 + q_offset
    visible = jnp.logical_or(not causal, j * block_k <= q_last)

    @pl.when(visible)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision) * sm_scale
        col = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = i * block_q + q_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, col <= row)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        ds = p * (dp - delta) * sm_scale
        dq_sc[:] = dq_sc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_sc, dv_sc, *,
                    sm_scale, causal, q_offset, kv_len, block_q, block_k,
                    precision):
    # grid: (BH, nk, nq) — q is the inner (sequential) axis
    j, i = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    q_last = (i + 1) * block_q - 1 + q_offset
    visible = jnp.logical_or(not causal, j * block_k <= q_last)

    @pl.when(visible)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision) * sm_scale
        col = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = i * block_q + q_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, col <= row)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)

        dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        ds = p * (dp - delta) * sm_scale
        dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _pad_len(s, block):
    return ((s + block - 1) // block) * block


def _pick_blocks(sq, skv):
    # v5e-measured defaults (BASELINE.md round-3 sweep, seq512):
    # 128x128 -> 65.5k tok/s (b16), 512x256 -> 96.6k, 512x512 -> 102.7k
    # (+57%; b64 103.1k = 38.3% MFU) — large tiles amortize the
    # (q, do, lse, delta) reloads across the k loop in the backward
    # kernels. VMEM at 512x512 f32 scores (d<=128) stays under the
    # ~16 MB budget. Override per run with MXNET_TPU_FLASH_BLOCK_Q/K.
    import os
    bq_cap = int(os.environ.get("MXNET_TPU_FLASH_BLOCK_Q", "512"))
    bk_cap = int(os.environ.get("MXNET_TPU_FLASH_BLOCK_K", "512"))
    bq = min(bq_cap, _pad_len(sq, 8))
    bk = min(bk_cap, _pad_len(skv, 128))
    return bq, bk


@x32
def _flash_fwd(q, k, v, sm_scale, causal, q_offset, interpret,
               block_q=None, block_k=None):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    bq0, bk0 = _pick_blocks(sq, skv)
    block_q = block_q or bq0
    block_k = block_k or bk0
    sq_p, skv_p = _pad_len(sq, block_q), _pad_len(skv, block_k)

    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, skv, d)
    vf = v.reshape(b * h, skv, d)
    if sq_p != sq:
        qf = jnp.pad(qf, ((0, 0), (0, sq_p - sq), (0, 0)))
    if skv_p != skv:
        kf = jnp.pad(kf, ((0, 0), (0, skv_p - skv), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, skv_p - skv), (0, 0)))

    bh = b * h
    nq, nk = sq_p // block_q, skv_p // block_k
    kern = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        q_offset=q_offset, kv_len=skv, block_q=block_q, block_k=block_k,
        precision=_dot_precision(q.dtype))
    o, lse = pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    o = o[:, :sq].reshape(b, h, sq, d)
    lse = lse[:, :sq, 0].reshape(b, h, sq)
    return o, lse


@x32
def _flash_bwd(q, k, v, o, lse, do, sm_scale, causal, q_offset, interpret,
               block_q=None, block_k=None):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    bq0, bk0 = _pick_blocks(sq, skv)
    block_q = block_q or bq0
    block_k = block_k or bk0
    sq_p, skv_p = _pad_len(sq, block_q), _pad_len(skv, block_k)
    bh = b * h

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(bh, sq, 1)
    qf = q.reshape(bh, sq, d)
    kf = k.reshape(bh, skv, d)
    vf = v.reshape(bh, skv, d)
    dof = do.reshape(bh, sq, d)
    lsef = lse.reshape(bh, sq, 1)
    if sq_p != sq:
        pad = ((0, 0), (0, sq_p - sq), (0, 0))
        qf, dof = jnp.pad(qf, pad), jnp.pad(dof, pad)
        # padded q rows: lse=-inf would give exp(s - -inf)=inf; use +inf
        # so p=exp(-inf)=0 for those rows
        lsef = jnp.pad(lsef, ((0, 0), (0, sq_p - sq), (0, 0)),
                       constant_values=jnp.inf)
        delta = jnp.pad(delta, ((0, 0), (0, sq_p - sq), (0, 0)))
    if skv_p != skv:
        pad = ((0, 0), (0, skv_p - skv), (0, 0))
        kf, vf = jnp.pad(kf, pad), jnp.pad(vf, pad)

    nq, nk = sq_p // block_q, skv_p // block_k
    common = dict(sm_scale=sm_scale, causal=causal, q_offset=q_offset,
                  kv_len=skv, block_q=block_q, block_k=block_k,
                  precision=_dot_precision(q.dtype))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, sq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, j, i: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), lambda b_, j, i: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b_, j, i: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b_, j, i: (b_, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, skv_p, d), k.dtype),
            jax.ShapeDtypeStruct((bh, skv_p, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta)

    dq = dq[:, :sq].reshape(b, h, sq, d)
    dk = dk[:, :skv].reshape(*k.shape)
    dv = dv[:, :skv].reshape(*v.shape)
    return dq, dk, dv


def flash_attention_with_lse(q, k, v, sm_scale=None, causal=False,
                             q_offset=0, interpret=None):
    """Forward-only flash attention returning (out, lse).

    lse has shape (B, H, Sq), fp32 — the ring-attention combiner state.
    Not differentiable through JAX autodiff (use flash_attention); ring
    attention defines its own VJP over the combined result.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash_fwd(q, k, v, sm_scale, bool(causal), int(q_offset),
                      resolve_interpret(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, sm_scale=None, causal=False, q_offset=0,
                    interpret=None):
    """softmax(q k^T * scale [+causal mask]) v, blockwise in VMEM."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    o, _ = _flash_fwd(q, k, v, sm_scale, bool(causal), int(q_offset),
                      resolve_interpret(interpret))
    return o


def _flash_vjp_fwd(q, k, v, sm_scale, causal, q_offset, interpret):
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    o, lse = _flash_fwd(q, k, v, sm_scale, bool(causal), int(q_offset),
                        resolve_interpret(interpret))
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(sm_scale, causal, q_offset, interpret, res, do):
    q, k, v, o, lse = res
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, sm_scale, bool(causal),
                            int(q_offset), resolve_interpret(interpret))
    return dq, dk, dv


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)
