"""Fused softmax cross-entropy Pallas kernel.

Reference analog: src/operator/nn/softmax.cc + the
softmax_cross_entropy op (src/operator/loss_binary_op.cc). The unfused
path materializes the full (N, V) log-softmax and its gradient in HBM;
for LM heads (V = 30k–250k) that doubles the activation-memory bill.
This kernel streams vocab blocks through VMEM: forward keeps only
(loss, lse) per row; backward reconstructs softmax(x) blockwise.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._util import resolve_interpret, x32

_NEG_INF = -1e30


def _xent_fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref,
                     m_sc, l_sc, corr_sc, *, v_len, block_n, block_v):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        corr_sc[:] = jnp.zeros_like(corr_sc)

    x = x_ref[:].astype(jnp.float32)
    col = j * block_v + lax.broadcasted_iota(jnp.int32, (block_n, block_v), 1)
    x = jnp.where(col < v_len, x, _NEG_INF)

    m_prev = m_sc[:]
    m_cur = jnp.maximum(m_prev, jnp.max(x, axis=1, keepdims=True))
    l_sc[:] = l_sc[:] * jnp.exp(m_prev - m_cur) + \
        jnp.sum(jnp.exp(x - m_cur), axis=1, keepdims=True)
    m_sc[:] = m_cur

    lab = lab_ref[:]  # (block_n, 1) int32
    hit = col == lab
    corr_sc[:] = corr_sc[:] + jnp.sum(jnp.where(hit, x, 0.0), axis=1,
                                      keepdims=True)

    @pl.when(j == nv - 1)
    def _():
        lse = m_sc[:] + jnp.log(l_sc[:])
        lse_ref[:] = lse
        loss_ref[:] = lse - corr_sc[:]


def _xent_bwd_kernel(x_ref, lab_ref, lse_ref, g_ref, dx_ref,
                     *, v_len, block_n, block_v):
    j = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32)
    col = j * block_v + lax.broadcasted_iota(jnp.int32, (block_n, block_v), 1)
    p = jnp.exp(jnp.where(col < v_len, x, _NEG_INF) - lse_ref[:])
    onehot = (col == lab_ref[:]).astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * g_ref[:]).astype(dx_ref.dtype)


def _pad_to(n, m):
    return ((n + m - 1) // m) * m


def _blocks(n, v):
    from ... import envvars
    bn = min(envvars.get("MXNET_TPU_XENT_BLOCK_N"), _pad_to(n, 8))
    bv = min(envvars.get("MXNET_TPU_XENT_BLOCK_V"), _pad_to(v, 128))
    return bn, bv


@x32
def _xent_fwd(logits, labels, interpret):
    """No explicit padding: Mosaic masks partial edge blocks (reads of
    the out-of-bounds tail are garbage but the kernel's col < v_len
    mask and the caller's row slice neutralize them)."""
    interpret = resolve_interpret(interpret)
    n, v = logits.shape
    bn, bv = _blocks(n, v)
    lab = labels.astype(jnp.int32).reshape(n, 1)

    loss, lse = pl.pallas_call(
        functools.partial(_xent_fwd_kernel, v_len=v, block_n=bn, block_v=bv),
        grid=(pl.cdiv(n, bn), pl.cdiv(v, bv)),
        in_specs=[
            pl.BlockSpec((bn, bv), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
        ],
        interpret=interpret,
    )(logits, lab)
    return loss[:, 0], lse[:, 0]


@x32
def _xent_bwd(logits, labels, lse, g, interpret):
    interpret = resolve_interpret(interpret)
    n, v = logits.shape
    bn, bv = _blocks(n, v)
    lab = labels.astype(jnp.int32).reshape(n, 1)
    lse2 = lse.reshape(n, 1)
    g2 = g.astype(jnp.float32).reshape(n, 1)

    dx = pl.pallas_call(
        functools.partial(_xent_bwd_kernel, v_len=v, block_n=bn, block_v=bv),
        grid=(pl.cdiv(n, bn), pl.cdiv(v, bv)),
        in_specs=[
            pl.BlockSpec((bn, bv), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, bv), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, v), logits.dtype),
        interpret=interpret,
    )(logits, lab, lse2, g2)
    return dx


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_xent_fused(logits, labels, interpret=None):
    """Per-row -log softmax(logits)[labels]. logits (N, V), labels (N,)."""
    loss, _ = _xent_fwd(logits, labels, interpret)
    return loss


def _xent_vjp_fwd(logits, labels, interpret):
    loss, lse = _xent_fwd(logits, labels, interpret)
    return loss, (logits, labels, lse)


def _xent_vjp_bwd(interpret, res, g):
    logits, labels, lse = res
    dx = _xent_bwd(logits, labels, lse, g, interpret)
    return dx, None


softmax_xent_fused.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)
