"""Fused whole-sequence LSTM Pallas kernel (the cuDNN-RNN analog).

Reference analog: ``src/operator/rnn.cc`` + ``cudnn_rnn-inl.h`` — the
fused multi-layer LSTM path behind ``gluon.rnn.LSTM``. The XLA
``lax.scan`` cell (op_impl_rnn._run_layer) runs the whole recurrence as
~T tiny dispatches inside a `while` loop: the (H, 4H) recurrent weight
streams from HBM every step and each iteration pays loop bookkeeping —
measured on the WikiText-2 LM config (650x2, b128, T=35) as ~0.9 ms of
scan ops plus ~2.7 ms of inter-iteration device idle per training step.

This kernel runs ONE grid pass over time with the recurrent weight
RESIDENT in VMEM (weight-stationary, ~3.4 MB at 650x2600 bf16) and the
(h, c) carry in f32 scratch. Forward emits the per-step h sequence plus
the (c_seq, gates) residuals the hand-written backward needs; backward
walks time in reverse via reversed BlockSpec index maps, accumulating
dW_h2h in a f32 VMEM scratch and emitting per-step pre-activation gate
gradients (``dgin``) from which the wrapper recovers dx / dW_i2h / db
with two large MXU matmuls outside the kernel.

Layout contract: gin/x are time-major ``(T, N, 4H)`` — exactly what
op_impl_rnn._run_layer already computes; w_h2h is ``(H, 4H)`` (the
transpose of the MXNet ``(4H, H)`` parameter block, done once outside).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._util import resolve_interpret, x32


def _lstm_fwd_kernel(gin_ref, w_ref, h0_ref, c0_ref,
                     out_ref, cseq_ref, gates_ref,
                     h_sc, c_sc, *, precision):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_sc[:] = h0_ref[:].astype(jnp.float32)
        c_sc[:] = c0_ref[:].astype(jnp.float32)

    h = h_sc[:].astype(w_ref.dtype)
    z = gin_ref[0].astype(jnp.float32) + jax.lax.dot_general(
        h, w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c_sc[:] + i * g
    h_new = o * jnp.tanh(c_new)
    out_ref[0] = h_new.astype(out_ref.dtype)
    cseq_ref[0] = c_new.astype(cseq_ref.dtype)
    gates_ref[0] = jnp.concatenate([i, f, g, o], axis=-1).astype(
        gates_ref.dtype)
    h_sc[:] = h_new
    c_sc[:] = c_new


def _lstm_bwd_kernel(gates_ref, cseq_ref, cprev_ref, hprev_ref,
                     dout_ref, dcseq_ref, w_ref, h0_ref, c0_ref,
                     dgin_ref, dh0_ref, dc0_ref, dw_ref,
                     dh_sc, dc_sc, dw_sc, *, precision):
    """Reverse-time step rt = T-1-t (the index maps flip time)."""
    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        dh_sc[:] = jnp.zeros_like(dh_sc)
        dc_sc[:] = jnp.zeros_like(dc_sc)
        dw_sc[:] = jnp.zeros_like(dw_sc)

    H = dh_sc.shape[-1]
    gts = gates_ref[0].astype(jnp.float32)
    i, f, g, o = (gts[:, :H], gts[:, H:2 * H], gts[:, 2 * H:3 * H],
                  gts[:, 3 * H:])
    c_t = cseq_ref[0].astype(jnp.float32)
    # at rt == 0 the "previous" state is the initial state
    first = t == T - 1
    c_prev = jnp.where(first, c0_ref[:].astype(jnp.float32),
                       cprev_ref[0].astype(jnp.float32))
    h_prev = jnp.where(first, h0_ref[:].astype(jnp.float32),
                       hprev_ref[0].astype(jnp.float32))

    tanh_c = jnp.tanh(c_t)
    dh = dout_ref[0].astype(jnp.float32) + dh_sc[:]
    dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_sc[:] \
        + dcseq_ref[0].astype(jnp.float32)
    do_ = dh * tanh_c * o * (1.0 - o)
    di = dc * g * i * (1.0 - i)
    df = dc * c_prev * f * (1.0 - f)
    dg = dc * i * (1.0 - g * g)
    dgin = jnp.concatenate([di, df, dg, do_], axis=-1)
    dgin_ref[0] = dgin.astype(dgin_ref.dtype)

    dginc = dgin.astype(w_ref.dtype)
    # dh_{t-1} = dgin @ W^T : (N, 4H) x (4H, H) contraction on 4H
    dh_sc[:] = jax.lax.dot_general(
        dginc, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)
    dc_sc[:] = dc * f
    # dW += h_{t-1}^T @ dgin : (H, N) x (N, 4H)
    dw_sc[:] = dw_sc[:] + jax.lax.dot_general(
        h_prev.astype(w_ref.dtype), dginc, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision)

    @pl.when(t == T - 1)
    def _():
        dh0_ref[:] = dh_sc[:].astype(dh0_ref.dtype)
        dc0_ref[:] = dc_sc[:].astype(dc0_ref.dtype)
        dw_ref[:] = dw_sc[:].astype(dw_ref.dtype)


def _dot_precision(dtype):
    return (lax.Precision.HIGHEST if jnp.dtype(dtype) == jnp.float32
            else lax.Precision.DEFAULT)


@x32
def _lstm_fwd(gin, w, h0, c0, interpret):
    T, N, G = gin.shape
    H = h0.shape[-1]
    kern = functools.partial(_lstm_fwd_kernel,
                             precision=_dot_precision(w.dtype))
    out, cseq, gates = pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, G), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((H, G), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, N, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, G), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, N, H), gin.dtype),
            jax.ShapeDtypeStruct((T, N, H), gin.dtype),
            jax.ShapeDtypeStruct((T, N, G), gin.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((N, H), jnp.float32),
        ],
        interpret=interpret,
    )(gin, w, h0, c0)
    return out, cseq, gates


@x32
def _lstm_bwd(gates, cseq, out, w, h0, c0, dout, dcseq, interpret):
    T, N, G = gates.shape
    H = h0.shape[-1]
    rt = lambda t: (T - 1 - t, 0, 0)  # reversed time
    rt_prev = lambda t: (jnp.maximum(T - 2 - t, 0), 0, 0)
    kern = functools.partial(_lstm_bwd_kernel,
                             precision=_dot_precision(w.dtype))
    dgin, dh0, dc0, dw = pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, G), rt, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, H), rt, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, H), rt_prev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, H), rt_prev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, H), rt, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, H), rt, memory_space=pltpu.VMEM),
            pl.BlockSpec((H, G), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, N, G), rt, memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((H, G), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, N, G), gates.dtype),
            jax.ShapeDtypeStruct((N, H), jnp.float32),
            jax.ShapeDtypeStruct((N, H), jnp.float32),
            jax.ShapeDtypeStruct((H, G), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((H, G), jnp.float32),
        ],
        interpret=interpret,
    )(gates, cseq, cseq, out, dout, dcseq, w, h0, c0)
    return dgin, dh0, dc0, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def lstm_layer_fused(gin, w_h2h_t, h0, c0, interpret=None):
    """One LSTM layer/direction over the whole sequence in one kernel.

    gin : (T, N, 4H) pre-computed input-side gate projections
        (x @ W_i2h^T + b_i2h + b_h2h), gate order (i, f, g, o).
    w_h2h_t : (H, 4H) recurrent weight, already transposed.
    h0, c0 : (N, H) initial state.
    Returns (out (T, N, H), c_seq (T, N, H)); the caller takes
    ``out[-1]`` / ``c_seq[-1]`` for the final state, so those
    cotangents flow through plain indexing into dout / dcseq.
    """
    out, cseq, _ = _lstm_fwd(gin, w_h2h_t, h0, c0,
                             resolve_interpret(interpret))
    return out, cseq


def _lstm_vjp_fwd(gin, w_h2h_t, h0, c0, interpret):
    out, cseq, gates = _lstm_fwd(gin, w_h2h_t, h0, c0,
                                 resolve_interpret(interpret))
    return (out, cseq), (gates, cseq, out, w_h2h_t, h0, c0)


def _lstm_vjp_bwd(interpret, res, cts):
    gates, cseq, out, w_h2h_t, h0, c0 = res
    dout, dcseq = cts
    dgin, dh0, dc0, dw = _lstm_bwd(gates, cseq, out, w_h2h_t, h0, c0,
                                   dout, dcseq,
                                   resolve_interpret(interpret))
    return (dgin, dw.astype(w_h2h_t.dtype), dh0.astype(h0.dtype),
            dc0.astype(c0.dtype))


lstm_layer_fused.defvjp(_lstm_vjp_fwd, _lstm_vjp_bwd)
