"""Shared helpers for the Pallas kernel wrappers."""
from __future__ import annotations

import functools

import jax


def x32(fn):
    """Trace ``fn`` with x64 disabled.

    The framework enables jax_enable_x64 globally (MXNet exposes
    int64/float64 NDArrays — base.py), but Mosaic requires i32 grid
    index maps and TPU hardware has no f64 anyway; tracing the kernel
    call under enable_x64(False) keeps every constant/iota i32. Tensor
    operands keep their concrete dtypes — the op layer only routes
    f32/bf16 here.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with jax.enable_x64(False):
            return fn(*args, **kwargs)

    return wrapper
