"""Shared helpers for the Pallas kernel wrappers."""
from __future__ import annotations

import functools

import jax

from ... import envvars

try:  # newer jax exports the x64 context manager at top level
    _enable_x64 = jax.enable_x64
except AttributeError:  # older jax: experimental namespace
    from jax.experimental import enable_x64 as _enable_x64

def interpret_mode() -> bool:
    """Run pallas_call in interpreter mode (CPU testing of kernels)."""
    return envvars.get("MXNET_TPU_PALLAS_INTERPRET")


def pallas_enabled() -> bool:
    """Should ops dispatch to the Pallas kernel path?"""
    if envvars.get("MXNET_TPU_DISABLE_PALLAS"):
        return False
    if interpret_mode():
        return True
    return jax.default_backend() == "tpu"


def pallas_ok_for(data) -> bool:
    """pallas_enabled() AND the value actually lives on (or is being
    traced for) a TPU device. In a TPU-backend process an op invoked on
    a cpu(0) context must NOT take the Mosaic path — it would crash at
    lowering ('Only interpret mode is supported on CPU backend')."""
    if not pallas_enabled():
        return False
    if interpret_mode():
        return True
    # jax.Array.devices() -> set[Device] classifies single- and
    # multi-device arrays uniformly (a CPU-mesh-sharded array in a TPU
    # process must refuse the Mosaic path). Tracers expose neither
    # .devices nor .device.
    devs = None
    devices_fn = getattr(data, "devices", None)
    if callable(devices_fn):
        try:
            devs = devices_fn()
        except Exception:
            devs = None
    if devs is None:
        dev = getattr(data, "device", None)
        if dev is not None and not callable(dev):
            devs = getattr(dev, "device_set", None)
            if not devs and hasattr(dev, "platform"):
                devs = [dev]
    if devs is None:
        # trace time: placement is the default device / backend
        dev = jax.config.jax_default_device
        if dev is None:
            return jax.default_backend() == "tpu"
        devs = [dev]
    # unknown platforms fail CLOSED — jnp fallback is always correct
    return {getattr(d, "platform", None) for d in devs} == {"tpu"}


def resolve_interpret(interpret):
    """``interpret=None`` (the public-entry default) means "whatever
    MXNET_TPU_PALLAS_INTERPRET says" — so call sites can't forget to
    thread the flag and crash compiling Mosaic off-TPU."""
    return interpret_mode() if interpret is None else interpret


def x32(fn):
    """Trace ``fn`` with x64 disabled.

    The framework enables jax_enable_x64 globally (MXNet exposes
    int64/float64 NDArrays — base.py), but Mosaic requires i32 grid
    index maps and TPU hardware has no f64 anyway; tracing the kernel
    call under enable_x64(False) keeps every constant/iota i32. Tensor
    operands keep their concrete dtypes — the op layer only routes
    f32/bf16 here.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with _enable_x64(False):
            return fn(*args, **kwargs)

    return wrapper
