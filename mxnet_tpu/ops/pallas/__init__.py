"""Pallas TPU kernel library.

The TPU-native analog of the reference's hand-written CUDA kernels —
the cuDNN operator family (src/operator/nn/cudnn/) and the fused
mshadow elementwise kernels (src/operator/mshadow_op.h). Where the
reference reaches for cuDNN/cuBLAS because XLA-era fusion didn't exist,
we only drop to Pallas where XLA's own fusion genuinely loses:

- ``layer_norm``  — one-pass fused normalize (HBM-bandwidth bound;
  keeps x in VMEM across the mean/var/normalize passes).
- ``flash_attention`` — blockwise softmax(QK^T)V with O(S) memory,
  the kernel the reference era composed out of batch_dot+softmax
  (SURVEY §5.7: no fused attention op exists upstream; this is the
  performance play for the BERT north star).
- ``softmax_xent`` — fused large-vocab softmax cross-entropy (LM
  heads: avoids materializing the (N, V) log-softmax for backward).
- ``lstm`` — whole-sequence fused LSTM layer (weight-stationary
  recurrent matmul + gates in one kernel; the cudnn_rnn-inl.h analog).

Dispatch contract: every kernel here has a pure-jnp twin used when the
backend is not TPU (tests run on the CPU mesh) or when
``MXNET_TPU_DISABLE_PALLAS=1``. ``MXNET_TPU_PALLAS_INTERPRET=1`` forces
the Pallas path in interpreter mode so the kernels themselves are
exercised off-TPU (the numerics tests do this).
"""
from __future__ import annotations

from ._util import interpret_mode, pallas_enabled, pallas_ok_for  # noqa: F401

from .layer_norm import layer_norm_fused  # noqa: E402
from .flash_attention import flash_attention, flash_attention_with_lse  # noqa: E402
from .flash_attention import (paged_attention_reference,  # noqa: E402
                              paged_flash_attention)
from .softmax_xent import softmax_xent_fused  # noqa: E402
from .lstm import lstm_layer_fused  # noqa: E402

__all__ = [
    "pallas_enabled",
    "pallas_ok_for",
    "interpret_mode",
    "layer_norm_fused",
    "flash_attention",
    "flash_attention_with_lse",
    "paged_flash_attention",
    "paged_attention_reference",
    "softmax_xent_fused",
    "lstm_layer_fused",
]
