"""Fused LayerNorm Pallas kernel (fwd + bwd).

Reference analog: src/operator/nn/layer_norm.cc (+ the CUDA
LayerNormGPU kernels in layer_norm.cu). The un-fused XLA lowering reads
x from HBM three times (mean, var, normalize); this kernel keeps a row
block resident in VMEM and does one pass, saving (mean, rstd) as
residuals for backward. dgamma/dbeta are accumulated across the
sequential TPU grid into the output refs.

Layout: the wrapper flattens any input to (R, D) over the normalized
(last) axis; rows are tiled (TILE_R, D) blocks. Non-last-axis LayerNorm
falls back to the jnp path (op_impl_nn.layer_norm).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._util import resolve_interpret, x32


def _pick_tile_r(n_rows: int, d: int) -> int:
    # keep the x block + fp32 temps well under VMEM (~16MB); 4 bytes/elt
    # fp32 working set ≈ 3 * TILE_R * D * 4
    budget = 2 * 1024 * 1024
    tile = max(8, min(256, budget // max(1, d * 4)))
    # round down to a multiple of 8 (fp32 sublane)
    tile = max(8, (tile // 8) * 8)
    return min(tile, max(8, ((n_rows + 7) // 8) * 8))


def _ln_fwd_kernel(x_ref, g_ref, b_ref, o_ref, mu_ref, rs_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rs = lax.rsqrt(var + eps)
    g = g_ref[:].astype(jnp.float32)
    b = b_ref[:].astype(jnp.float32)
    o_ref[:] = (xc * rs * g + b).astype(o_ref.dtype)
    mu_ref[:] = mu
    rs_ref[:] = rs


def _ln_bwd_kernel(x_ref, g_ref, mu_ref, rs_ref, dy_ref,
                   dx_ref, dg_ref, db_ref, *, n_rows, tile_r):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    mu = mu_ref[:]
    rs = rs_ref[:]
    xhat = (x - mu) * rs
    dxhat = dy * g
    m1 = jnp.mean(dxhat, axis=1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=1, keepdims=True)
    dx_ref[:] = (rs * (dxhat - m1 - xhat * m2)).astype(dx_ref.dtype)

    # dgamma/dbeta: reduce over rows; TPU grid iterations run
    # sequentially, so accumulate into the (1, D) output refs. Rows past
    # n_rows are block padding (garbage reads) — mask them out.
    d = x.shape[1]
    row = i * tile_r + lax.broadcasted_iota(jnp.int32, (tile_r, d), 0)
    valid = row < n_rows
    pg = jnp.sum(jnp.where(valid, dy * xhat, 0.0), axis=0, keepdims=True)
    pb = jnp.sum(jnp.where(valid, dy, 0.0), axis=0, keepdims=True)

    @pl.when(i == 0)
    def _():
        dg_ref[:] = pg
        db_ref[:] = pb

    @pl.when(i > 0)
    def _():
        dg_ref[:] = dg_ref[:] + pg
        db_ref[:] = db_ref[:] + pb


@x32
def _ln_fwd(x2, gamma, beta, eps, interpret):
    r, d = x2.shape
    tile = _pick_tile_r(r, d)
    grid = (pl.cdiv(r, tile),)
    out, mu, rs = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r, d), x2.dtype),
            jax.ShapeDtypeStruct((r, 1), jnp.float32),
            jax.ShapeDtypeStruct((r, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma.reshape(1, d), beta.reshape(1, d))
    return out, mu, rs


@x32
def _ln_bwd(x2, gamma, mu, rs, dy2, interpret):
    r, d = x2.shape
    tile = _pick_tile_r(r, d)
    grid = (pl.cdiv(r, tile),)
    dx, dg, db = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, n_rows=r, tile_r=tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r, d), x2.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(x2, gamma.reshape(1, d), mu, rs, dy2)
    return dx, dg, db


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm_fused(x, gamma, beta, eps=1e-5, interpret=None):
    """Fused LayerNorm over the last axis. Any leading shape."""
    out, _, _ = _ln_res(x, gamma, beta, eps, interpret)
    return out


def _ln_res(x, gamma, beta, eps, interpret):
    interpret = resolve_interpret(interpret)
    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    out, mu, rs = _ln_fwd(x2, gamma, beta, eps, interpret)
    return out.reshape(shape), mu, rs


def _layer_norm_vjp_fwd(x, gamma, beta, eps, interpret):
    out, mu, rs = _ln_res(x, gamma, beta, eps, interpret)
    return out, (x, gamma, mu, rs)


def _layer_norm_vjp_bwd(eps, interpret, res, dy):
    interpret = resolve_interpret(interpret)
    x, gamma, mu, rs = res
    shape = x.shape
    d = shape[-1]
    dx, dg, db = _ln_bwd(x.reshape(-1, d), gamma, mu, rs,
                         dy.reshape(-1, d), interpret)
    return (dx.reshape(shape), dg.reshape(gamma.shape).astype(gamma.dtype),
            db.reshape(gamma.shape).astype(gamma.dtype))


layer_norm_fused.defvjp(_layer_norm_vjp_fwd, _layer_norm_vjp_bwd)
