"""Global PRNG state.

The reference gives every device a persistent PRNG resource
(src/common/random_generator.h, ResourceRequest::kRandom,
src/resource.cc) seeded by ``mx.random.seed``. TPU-native analog: a
process-global threefry key chain — ``seed()`` resets the chain, every
sampling op splits one subkey off it. Under ``hybridize()`` tracing, the
chain can be overridden with a traced key (``push_trace_key``) so
compiled graphs get a fresh key argument per call instead of a baked-in
constant — the functional-RNG discipline XLA requires.
"""
from __future__ import annotations

import threading
import time

import jax

__all__ = ["seed", "get_state"]


class _RandomState:
    """Process-global key chain (mx.random.seed must govern ALL threads,
    like the reference seeding every device RNG resource) with a lock;
    trace-key overrides are per-thread (a jit trace runs on one thread).
    """

    def __init__(self):
        # the key is materialized LAZILY: creating a PRNGKey initializes
        # the JAX backend, and that must not happen at import time —
        # mx.kv.create('dist_sync') needs to run
        # jax.distributed.initialize first (multi-process rendezvous is
        # impossible once the local backend is up)
        self._seed = int(time.time() * 1e6) % (2**31)
        self._key = None
        self.lock = threading.Lock()
        self._tls = threading.local()

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        return self._key

    @key.setter
    def key(self, k):
        self._key = k

    @property
    def trace_keys(self):
        if not hasattr(self._tls, "trace_keys"):
            self._tls.trace_keys = []
        return self._tls.trace_keys


_STATE = _RandomState()


def seed(seed_state: int, ctx="all"):
    """mx.random.seed — reset the global key chain (all threads)."""
    with _STATE.lock:
        _STATE.key = jax.random.PRNGKey(int(seed_state))


def _next_key():
    """Split one subkey off the chain (or off the traced key in a trace)."""
    tk = _STATE.trace_keys
    if tk:
        k, sub = jax.random.split(tk[-1])
        tk[-1] = k
        return sub
    with _STATE.lock:
        _STATE.key, sub = jax.random.split(_STATE.key)
    return sub


def push_trace_key(key):
    _STATE.trace_keys.append(key)


def pop_trace_key():
    return _STATE.trace_keys.pop()


def get_state():
    return _STATE
