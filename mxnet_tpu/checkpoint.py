"""Preemption-aware checkpoint / resume.

The reference's recovery story is "checkpoint + manual restart"
(SURVEY §5.3: ps-lite heartbeats exist but nothing elastic; §5.4:
save_checkpoint/load_checkpoint). TPU fleets add a harder requirement —
preemption with a short grace window — so this module is the planned
§5.3 extension: a :class:`CheckpointManager` that

- saves periodically (``every_n_steps``) through the normal parameter/
  trainer-state serialization (``.params``/``.states`` + a JSON meta
  sidecar);
- installs signal handlers (SIGTERM by default — the preemption notice)
  that snapshot IMMEDIATELY and then re-deliver to any previous
  handler;
- prunes to the newest ``max_keep`` checkpoints;
- discovers the latest checkpoint at startup (``latest_step`` /
  ``restore``) so a restarted job resumes where it died.

Multi-host: every process calls ``step()`` at the same cadence (SPMD);
only process 0 writes the single-file checkpoint unless
``sharded=True``, in which case each process writes its shards through
``nd.save_sharded``.
"""
from __future__ import annotations

import glob
import json
import os
import signal as _signal
import time

from .base import MXNetError

__all__ = ["CheckpointManager"]


class CheckpointManager:
    def __init__(self, prefix, net=None, trainer=None, max_keep=5,
                 every_n_steps=None, signals=(_signal.SIGTERM,),
                 sharded=False):
        self._prefix = prefix
        self._net = net
        self._trainer = trainer
        self._max_keep = max_keep
        self._every = every_n_steps
        self._sharded = sharded
        self._step = 0
        self._preempted = False
        self._prev_handlers = {}
        for sig in signals or ():
            try:
                self._prev_handlers[sig] = _signal.signal(sig, self._on_signal)
            except (ValueError, OSError):
                pass  # not on the main thread / unsupported signal

    # -- signal path -------------------------------------------------------
    def _on_signal(self, signum, frame):
        """Preemption notice: snapshot NOW (the grace window may be
        seconds), then re-deliver with the previous disposition — a
        SIG_DFL SIGTERM must still terminate the process (swallowing it
        would make the job ignore kill requests)."""
        self._preempted = True
        try:
            self.save(tag="preempt")
        finally:
            prev = self._prev_handlers.get(signum)
            if callable(prev):
                prev(signum, frame)
            elif prev == _signal.SIG_DFL:
                _signal.signal(signum, _signal.SIG_DFL)
                os.kill(os.getpid(), signum)
            # SIG_IGN: swallow, matching the prior disposition

    @property
    def preempted(self):
        return self._preempted

    # -- cadence -----------------------------------------------------------
    def step(self, increment=1):
        """Advance the step counter; save when the cadence fires. Call
        once per optimizer step (or per epoch with every_n_steps=1)."""
        self._step += increment
        if self._every and self._step % self._every == 0:
            self.save()
        return self._step

    # -- save / prune ------------------------------------------------------
    def _rank(self):
        try:
            import jax
            return jax.process_index(), jax.process_count()
        except Exception:
            return 0, 1

    def save(self, tag=None):
        from . import ndarray as nd

        rank, nproc = self._rank()
        base = f"{self._prefix}-{self._step:07d}"
        wrote = []
        if self._net is not None:
            if self._sharded:
                params = {name: p.data()
                          for name, p in self._net.collect_params().items()}
                wrote.append(nd.save_sharded(base, params))
            elif rank == 0:
                self._net.save_parameters(base + ".params")
                wrote.append(base + ".params")
        if self._trainer is not None and rank == 0:
            self._trainer.save_states(base + ".states")
            wrote.append(base + ".states")
        if rank == 0:
            meta = {"step": self._step, "time": time.time(),
                    "tag": tag or "periodic", "sharded": self._sharded,
                    "num_processes": nproc}
            with open(base + ".meta.json", "w") as f:
                json.dump(meta, f)
            wrote.append(base + ".meta.json")
            self._prune()
        return wrote

    def _checkpoints(self):
        metas = sorted(glob.glob(f"{self._prefix}-*.meta.json"))
        out = []
        for m in metas:
            try:
                with open(m) as f:
                    out.append((json.load(f)["step"], m[:-len(".meta.json")]))
            except (ValueError, KeyError):
                continue
        return sorted(out)

    def _prune(self):
        ckpts = self._checkpoints()
        for _, base in ckpts[:-self._max_keep] if self._max_keep else []:
            for f in glob.glob(base + ".*"):  # incl. .shard-* files
                try:
                    os.remove(f)
                except OSError:
                    pass

    # -- resume ------------------------------------------------------------
    def latest_step(self):
        """Step of the newest checkpoint, or None if none exist."""
        ckpts = self._checkpoints()
        return ckpts[-1][0] if ckpts else None

    def restore(self, net=None, trainer=None):
        """Load the newest checkpoint into net/trainer; returns its step
        (0 when nothing to restore — fresh start)."""
        from . import ndarray as nd

        ckpts = self._checkpoints()
        if not ckpts:
            return 0
        step, base = ckpts[-1]
        with open(base + ".meta.json") as f:
            meta = json.load(f)
        net = net or self._net
        trainer = trainer or self._trainer
        if net is not None:
            if meta.get("sharded"):
                params = nd.load_sharded(base)
                pd = net.collect_params()
                for name, arr in params.items():
                    pd[name].set_data(arr)
            else:
                net.load_parameters(base + ".params")
        if trainer is not None and os.path.exists(base + ".states"):
            trainer.load_states(base + ".states")
        self._step = step
        return step

    def close(self):
        """Restore the previous signal handlers."""
        for sig, prev in self._prev_handlers.items():
            try:
                _signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()
