"""Image loading & augmentation (python/mxnet/image/image.py analog).

The reference pipeline (src/io/image_aug_default.cc DefaultImageAugmenter
+ iter_image_recordio_2.cc) does decode→resize→crop→flip→color-jitter→
normalize on CPU worker threads. Here the augmenter chain is numpy
(PIL for codecs), run in the iterator's prefetch thread; the output
lands as one batched device array per step (single H2D per batch beats
the reference's per-image copies).
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .context import current_context
from .io.io import DataIter, DataBatch, DataDesc
from .ndarray import array as nd_array
from . import recordio as _recordio

__all__ = [
    "imresize", "imdecode", "resize_short", "fixed_crop", "center_crop",
    "random_crop", "color_normalize", "HorizontalFlipAug", "CastAug",
    "ColorNormalizeAug", "ForceResizeAug", "ResizeAug", "CenterCropAug",
    "RandomCropAug", "RandomSizedCropAug", "CreateAugmenter", "Augmenter", "ImageIter",
    "ImageRecordIterPy", "BrightnessJitterAug", "ContrastJitterAug",
    "SaturationJitterAug", "HueJitterAug", "LightingAug", "RandomGrayAug",
    "RandomOrderAug", "ColorJitterAug",
]


def imdecode(buf, to_rgb=1, **kwargs):
    raw = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, bytearray)) else buf
    img = _recordio._decode_image(raw)
    if img.ndim == 2:
        img = np.stack([img] * 3, axis=-1)
    return nd_array(img)


def imresize(src, w, h, interp=1):
    img = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    out = _resize_np(img, w, h)
    return nd_array(out)


def _resize_np(img, w, h):
    """Bilinear resize in numpy (no OpenCV in the TPU image)."""
    ih, iw = img.shape[:2]
    if (ih, iw) == (h, w):
        return img.copy()
    ys = np.linspace(0, ih - 1, h)
    xs = np.linspace(0, iw - 1, w)
    y0 = np.floor(ys).astype(int); y1 = np.minimum(y0 + 1, ih - 1)
    x0 = np.floor(xs).astype(int); x1 = np.minimum(x0 + 1, iw - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    im = img.astype(np.float32)
    if im.ndim == 2:
        im = im[:, :, None]
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


def resize_short(src, size, interp=2):
    img = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = img.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return nd_array(_resize_np(img, new_w, new_h))


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    img = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    out = img[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _resize_np(out, size[0], size[1])
    return nd_array(out)


def center_crop(src, size, interp=2):
    img = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = img.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size), (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    img = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = img.shape[:2]
    new_w, new_h = size
    x0 = np.random.randint(0, max(1, w - new_w + 1))
    y0 = np.random.randint(0, max(1, h - new_h + 1))
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size), (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    img = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    img = img.astype(np.float32) - np.asarray(mean, np.float32)
    if std is not None:
        img = img / np.asarray(std, np.float32)
    return nd_array(img)


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        img = np.asarray(src)
        return _resize_np(img, self.size[0], self.size[1])


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        return np.asarray(resize_short(src, self.size))


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        out, _ = center_crop(src, self.size)
        return np.asarray(out)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size = size

    def __call__(self, src):
        out, _ = random_crop(src, self.size)
        return np.asarray(out)


class RandomSizedCropAug(Augmenter):
    """Inception-style random area/aspect crop resized to ``size``
    (reference RandomSizedCropAug: area in [0.08, 1], aspect in
    [3/4, 4/3], 10 attempts then center-crop fallback)."""

    def __init__(self, size, area=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interp=2):
        super().__init__(size=size, area=area, ratio=ratio)
        self.size = size
        self.area = area if isinstance(area, (tuple, list)) else (area, 1.0)
        self.ratio = ratio

    def __call__(self, src):
        img = np.asarray(src)
        h, w = img.shape[:2]
        src_area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self.area) * src_area
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            aspect = np.exp(np.random.uniform(*log_ratio))
            new_w = int(round(np.sqrt(target_area * aspect)))
            new_h = int(round(np.sqrt(target_area / aspect)))
            if new_w <= w and new_h <= h and new_w > 0 and new_h > 0:
                x0 = np.random.randint(0, w - new_w + 1)
                y0 = np.random.randint(0, h - new_h + 1)
                crop = img[y0:y0 + new_h, x0:x0 + new_w]
                return _resize_np(crop, self.size[0], self.size[1])
        out, _ = center_crop(src, self.size)
        return np.asarray(out)


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        img = np.asarray(src)
        if np.random.random() < self.p:
            img = img[:, ::-1]
        return img


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return np.asarray(src).astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=list(np.atleast_1d(mean)), std=list(np.atleast_1d(std)))
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, src):
        return (np.asarray(src).astype(np.float32) - self.mean) / self.std


_GRAY_COEF = np.array([0.299, 0.587, 0.114], np.float32)


class BrightnessJitterAug(Augmenter):
    """Scale pixel values by 1 + U(-brightness, brightness)
    (reference BrightnessJitterAug)."""

    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + np.random.uniform(-self.brightness, self.brightness)
        return np.asarray(src).astype(np.float32) * alpha


class ContrastJitterAug(Augmenter):
    """Blend with the mean gray level (reference ContrastJitterAug)."""

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + np.random.uniform(-self.contrast, self.contrast)
        img = np.asarray(src).astype(np.float32)
        gray_mean = (img * _GRAY_COEF).sum(axis=-1).mean()
        return img * alpha + gray_mean * (1.0 - alpha)


class SaturationJitterAug(Augmenter):
    """Blend with the per-pixel gray image (reference
    SaturationJitterAug)."""

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + np.random.uniform(-self.saturation, self.saturation)
        img = np.asarray(src).astype(np.float32)
        gray = (img * _GRAY_COEF).sum(axis=-1, keepdims=True)
        return img * alpha + gray * (1.0 - alpha)


class HueJitterAug(Augmenter):
    """Rotate hue in YIQ space by U(-hue, hue) (reference HueJitterAug
    — same tyiq/ityiq matrix approximation)."""

    _TYIQ = np.array([[0.299, 0.587, 0.114],
                      [0.596, -0.274, -0.321],
                      [0.211, -0.523, 0.311]], np.float32)
    _ITYIQ = np.array([[1.0, 0.956, 0.621],
                       [1.0, -0.272, -0.647],
                       [1.0, -1.107, 1.705]], np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = np.random.uniform(-self.hue, self.hue)
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        rot = np.array([[1.0, 0.0, 0.0],
                        [0.0, u, -w],
                        [0.0, w, u]], np.float32)
        t = self._ITYIQ @ rot @ self._TYIQ
        img = np.asarray(src).astype(np.float32)
        return img @ t.T


class LightingAug(Augmenter):
    """AlexNet-style PCA lighting noise (reference LightingAug):
    add eigvec @ (N(0, alphastd) * eigval) per image."""

    _EIGVAL = np.array([55.46, 4.794, 1.148], np.float32)
    _EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alphastd):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,)).astype(np.float32)
        rgb = self._EIGVEC @ (alpha * self._EIGVAL)
        return np.asarray(src).astype(np.float32) + rgb


class RandomGrayAug(Augmenter):
    """With probability p replace the image by its 3-channel gray
    version (reference RandomGrayAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        img = np.asarray(src).astype(np.float32)
        if np.random.rand() < self.p:
            gray = (img * _GRAY_COEF).sum(axis=-1, keepdims=True)
            img = np.repeat(gray, 3, axis=-1)
        return img


class RandomOrderAug(Augmenter):
    """Apply child augmenters in random order (reference
    RandomOrderAug — the ColorJitter composition uses it)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def dumps(self):
        """Nest the children (reference RandomOrderAug.dumps)."""
        import json
        return json.dumps([type(self).__name__,
                           [json.loads(t.dumps()) for t in self.ts]])

    def __call__(self, src):
        order = np.random.permutation(len(self.ts))
        for i in order:
            src = self.ts[i](src)
        return src


class ColorJitterAug(RandomOrderAug):
    """Random-order brightness/contrast/saturation jitter (reference
    ColorJitterAug — a RandomOrderAug subclass, so isinstance checks
    ported from upstream keep working)."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)
        self._kwargs = {"brightness": brightness, "contrast": contrast,
                        "saturation": saturation}


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter chain (python/mxnet/image
    CreateAugmenter), photometric jitters included in the reference's
    order: geometric -> cast -> color jitter -> hue -> lighting ->
    gray -> normalize."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        # reference: rand_resize implies random crop (area/aspect jitter)
        auglist.append(RandomSizedCropAug(crop_size, interp=inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(pca_noise))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Python-side image iterator over RecordIO or image list
    (python/mxnet/image/image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", dtype="float32", ctx=None, **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or imglist is not None
        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.label_width = label_width
        self.ctx = ctx or current_context()
        self.dtype = dtype
        self.data_name = data_name
        self.label_name = label_name
        self.imgrec = None
        self.seq = None
        self.imglist = {}
        if path_imgrec:
            if path_imgidx:
                self.imgrec = _recordio.MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = _recordio.MXRecordIO(path_imgrec, "r")
        elif path_imglist:
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    label = np.array(parts[1:-1], dtype=np.float32)
                    self.imglist[int(parts[0])] = (label, parts[-1])
            self.seq = list(self.imglist.keys())
            self.path_root = path_root
        elif imglist is not None:
            for i, (label, path) in enumerate(imglist):
                self.imglist[i] = (np.atleast_1d(np.asarray(label, np.float32)), path)
            self.seq = list(self.imglist.keys())
            self.path_root = path_root
        if num_parts > 1 and self.seq is not None:
            self.seq = self.seq[part_index::num_parts]
        self.shuffle = shuffle
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter((data_shape[0], data_shape[1], data_shape[2]), **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_resize", "rand_mirror",
                         "mean", "std", "brightness", "contrast",
                         "saturation", "hue", "pca_noise", "rand_gray",
                         "inter_method")})
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape, self.dtype)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape, self.dtype)]

    def reset(self):
        if self.shuffle and self.seq is not None:
            np.random.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = _recordio.unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            with open(f"{self.path_root}/{fname}", "rb") as f:
                return label, f.read()
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = _recordio.unpack(s)
        return header.label, img

    def next(self):
        batch_data = np.zeros((self.batch_size,) + self.data_shape, dtype=self.dtype)
        batch_label = np.zeros((self.batch_size, self.label_width), dtype=np.float32)
        i = 0
        pad = 0
        try:
            while i < self.batch_size:
                label, raw = self.next_sample()
                img = np.asarray(imdecode(raw))
                for aug in self.auglist:
                    img = aug(img)
                batch_data[i] = np.transpose(img, (2, 0, 1))  # HWC→CHW
                batch_label[i] = np.atleast_1d(np.asarray(label, np.float32))[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = self.batch_size - i
        label_out = batch_label[:, 0] if self.label_width == 1 else batch_label
        return DataBatch(data=[nd_array(batch_data, ctx=self.ctx)],
                         label=[nd_array(label_out, ctx=self.ctx)], pad=pad)

    def iter_next(self):
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            return False


class ImageRecordIterPy(ImageIter):
    """ImageRecordIter with the native IO fast path (same kwargs surface
    as the reference C++ iterator). Record framing + shuffling +
    threaded batch prefetch run in the C++ library
    (src/cc/recordio.cc); decode+augment run per batch on the Python
    side; the batch lands as one contiguous device_put."""

    def __init__(self, path_imgrec=None, path_imgidx=None, data_shape=None,
                 batch_size=1, shuffle=False, rand_crop=False,
                 rand_mirror=False, mean_r=0, mean_g=0, mean_b=0,
                 std_r=1, std_g=1, std_b=1, num_parts=1, part_index=0,
                 preprocess_threads=4, prefetch_buffer=4, label_width=1,
                 resize=0, seed=0, **kwargs):
        mean = None
        std = None
        if mean_r or mean_g or mean_b:
            mean = np.array([mean_r, mean_g, mean_b], np.float32)
            std = np.array([std_r or 1, std_g or 1, std_b or 1], np.float32)
        aug = CreateAugmenter(
            (data_shape[0], data_shape[1], data_shape[2]),
            resize=resize, rand_crop=rand_crop, rand_mirror=rand_mirror,
            mean=mean, std=std,
            # photometric kwargs forward too (reference ImageRecordIter
            # max_random_* params; same silent-drop bug as ImageIter had)
            **{k: v for k, v in kwargs.items()
               if k in ("rand_resize", "brightness", "contrast",
                        "saturation", "hue", "pca_noise", "rand_gray",
                        "inter_method")})
        self._native = None  # before super().__init__ — it calls reset()
        super().__init__(batch_size, data_shape, label_width=label_width,
                         path_imgrec=path_imgrec, path_imgidx=path_imgidx,
                         shuffle=shuffle, num_parts=num_parts,
                         part_index=part_index, aug_list=aug)
        if path_imgrec:
            try:
                from .io.native import NativeBatcher
                self._native = NativeBatcher(
                    path_imgrec, path_imgidx, batch_size=batch_size,
                    num_threads=preprocess_threads, shuffle=shuffle,
                    seed=seed, num_parts=num_parts, part_index=part_index)
            except Exception:
                self._native = None  # python fallback path

    def reset(self):
        if self._native is not None:
            self._native.reset()
            return
        super().reset()

    def next(self):
        if self._native is None:
            return super().next()
        records = self._native.next()
        if records is None:
            raise StopIteration
        batch_data = np.zeros((self.batch_size,) + self.data_shape,
                              dtype=self.dtype)
        batch_label = np.zeros((self.batch_size, self.label_width), np.float32)
        from . import recordio as _rio
        for i, raw in enumerate(records):
            header, img_bytes = _rio.unpack(raw)
            img = np.asarray(imdecode(img_bytes))
            for aug in self.auglist:
                img = aug(img)
            batch_data[i] = np.transpose(img, (2, 0, 1))
            batch_label[i] = np.atleast_1d(
                np.asarray(header.label, np.float32))[:self.label_width]
        pad = self.batch_size - len(records)
        label_out = batch_label[:, 0] if self.label_width == 1 else batch_label
        return DataBatch(data=[nd_array(batch_data, ctx=self.ctx)],
                         label=[nd_array(label_out, ctx=self.ctx)], pad=pad)


# ----------------------------------------------------------------------
# Detection augmenters (python/mxnet/image/detection.py analog).
# Labels are MXNet detection format: (N, 5+) float rows
# [class_id, xmin, ymin, xmax, ymax, ...] with coordinates normalized
# to [0, 1]. Each augmenter maps (img, label) -> (img, label).
# ----------------------------------------------------------------------
class DetAugmenter:
    """Detection augmenter base (reference DetAugmenter)."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only augmenter into the detection chain
    (geometry-preserving ops only — reference DetBorrowAug)."""

    def __init__(self, augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image AND box x-coordinates with probability p."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if np.random.rand() < self.p:
            src = src[:, ::-1]
            label = label.copy()
            xmin = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - xmin
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping enough box overlap (simplified reference
    DetRandomCropAug: IOU-style constraint via min box coverage)."""

    def __init__(self, min_object_covered=0.5, min_crop_scale=0.5,
                 max_attempts=25):
        self.min_object_covered = min_object_covered
        self.min_crop_scale = min_crop_scale
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        h, w = src.shape[:2]
        for _ in range(self.max_attempts):
            s = np.random.uniform(self.min_crop_scale, 1.0)
            cw, ch = int(w * s), int(h * s)
            x0 = np.random.randint(0, w - cw + 1)
            y0 = np.random.randint(0, h - ch + 1)
            new_label = self._crop_boxes(label, x0 / w, y0 / h, cw / w, ch / h)
            if len(new_label):
                return src[y0:y0 + ch, x0:x0 + cw], new_label
        return src, label

    def _crop_boxes(self, label, cx, cy, cw, ch):
        out = []
        for row in label:
            xmin, ymin, xmax, ymax = row[1:5]
            ixmin, iymin = max(xmin, cx), max(ymin, cy)
            ixmax, iymax = min(xmax, cx + cw), min(ymax, cy + ch)
            iw, ih = max(ixmax - ixmin, 0.0), max(iymax - iymin, 0.0)
            area = (xmax - xmin) * (ymax - ymin)
            if area <= 0 or iw * ih / area < self.min_object_covered:
                continue
            new = row.copy()
            new[1] = (ixmin - cx) / cw
            new[2] = (iymin - cy) / ch
            new[3] = (ixmax - cx) / cw
            new[4] = (iymax - cy) / ch
            out.append(new)
        return np.asarray(out, label.dtype).reshape(-1, label.shape[1])


class DetRandomPadAug(DetAugmenter):
    """Random expand-pad; boxes shrink into the padded canvas
    (reference DetRandomPadAug)."""

    def __init__(self, max_pad_scale=2.0, pad_val=(127, 127, 127)):
        self.max_pad_scale = max_pad_scale
        self.pad_val = pad_val

    def __call__(self, src, label):
        h, w, c = src.shape
        s = np.random.uniform(1.0, self.max_pad_scale)
        nh, nw = int(h * s), int(w * s)
        y0 = np.random.randint(0, nh - h + 1)
        x0 = np.random.randint(0, nw - w + 1)
        canvas = np.empty((nh, nw, c), src.dtype)
        canvas[...] = np.asarray(self.pad_val, src.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = src
        label = label.copy()
        label[:, 1] = (label[:, 1] * w + x0) / nw
        label[:, 3] = (label[:, 3] * w + x0) / nw
        label[:, 2] = (label[:, 2] * h + y0) / nh
        label[:, 4] = (label[:, 4] * h + y0) / nh
        return canvas, label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one augmenter from a list (or skip)."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if np.random.rand() >= self.skip_prob and self.aug_list:
            aug = self.aug_list[np.random.randint(len(self.aug_list))]
            return aug(src, label)
        return src, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None,
                       min_object_covered=0.5, max_pad_scale=2.0,
                       inter_method=2, **kwargs):
    """Build the detection augmenter chain (reference
    CreateDetAugmenter): geometric det augmenters + borrowed pixel
    augmenters + final resize to data_shape."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        auglist.append(DetRandomSelectAug(
            [DetRandomCropAug(min_object_covered=min_object_covered)],
            skip_prob=1.0 - rand_crop))
    if rand_pad > 0:
        auglist.append(DetRandomSelectAug(
            [DetRandomPadAug(max_pad_scale=max_pad_scale)],
            skip_prob=1.0 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(ForceResizeAug((data_shape[2], data_shape[1]),
                                               inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


__all__ += ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
            "DetRandomCropAug", "DetRandomPadAug", "DetRandomSelectAug",
            "CreateDetAugmenter"]


class ImageDetIter(ImageIter):
    """Detection data iterator (reference python/mxnet/image/detection.py
    ImageDetIter): images + variable-count box labels, batched with the
    label tensor padded to ``label_shape`` with -1 rows — exactly the
    (B, M, 5) format ``contrib.MultiBoxTarget`` consumes.

    Per-sample labels accept either the already-2D (N, obj_width) form
    or the im2rec flat detection packing ``[A, B, <A-2 extra header>,
    obj0 ... objN]`` where A is the header width and B the object width
    (reference ImageDetIter._parse_label).
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="label",
                 label_shape=None, ctx=None, **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_pad", "rand_mirror",
                         "mean", "std", "min_object_covered",
                         "max_pad_scale", "inter_method")})
        # detection augmenters run as (img, label) pairs in next();
        # pass an EMPTY pixel chain to the base iterator
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name,
                         ctx=ctx)
        self.det_auglist = aug_list
        if label_shape is None:
            label_shape = self._estimate_label_shape()
        self.label_shape = tuple(label_shape)

    @staticmethod
    def _parse_label(label):
        """Flat im2rec det packing or 2-D array -> (N, obj_width)."""
        arr = np.asarray(label, np.float32)
        if arr.ndim == 2:
            return arr
        raw = arr.ravel()
        if raw.size < 2:
            raise ValueError("invalid detection label (needs header)")
        a, b = int(raw[0]), int(raw[1])
        if b <= 0:
            raise ValueError(
                f"detection label: header object width {b} must be positive")
        if a < 2 or a > raw.size:
            # a == raw.size is a legal header-only label: a negative
            # sample with zero objects -> (0, b)
            raise ValueError(
                f"detection label: header width {a} out of range for a "
                f"label of {raw.size} values")
        objs = raw[a:]
        n = objs.size // b
        if n * b != objs.size:
            raise ValueError(
                f"detection label: {objs.size} values not divisible by "
                f"object width {b}")
        return objs[: n * b].reshape(n, b)

    def _estimate_label_shape(self):
        """Scan ALL samples for (max_objects, obj_width) — including
        the RecordIO path, where labels only surface through
        next_sample (reference _estimate_label_shape does the same
        full pass, then resets)."""
        max_n, width = 0, 5
        self.reset()
        try:
            while True:
                label, _ = self.next_sample()
                parsed = self._parse_label(label)
                max_n = max(max_n, parsed.shape[0])
                width = max(width, parsed.shape[1])
        except StopIteration:
            pass
        self.reset()
        return (max(max_n, 1), width)

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size,) + self.label_shape, "float32")]

    def sync_label_shape(self, it, verbose=False):
        """Synchronize label padding with another ImageDetIter (train /
        val pairs must agree — reference sync_label_shape)."""
        shape = (max(self.label_shape[0], it.label_shape[0]),
                 max(self.label_shape[1], it.label_shape[1]))
        self.label_shape = shape
        it.label_shape = shape
        return it

    def next(self):
        bs = self.batch_size
        m, w = self.label_shape
        batch_data = np.zeros((bs,) + self.data_shape, dtype=self.dtype)
        batch_label = -np.ones((bs, m, w), np.float32)
        i = 0
        pad = 0
        try:
            while i < bs:
                label, raw = self.next_sample()
                img = np.asarray(imdecode(raw)).astype(np.float32)
                parsed = self._parse_label(label)
                for aug in self.det_auglist:
                    img, parsed = aug(img, parsed)
                if parsed.shape[1] > w:
                    raise ValueError(
                        f"ImageDetIter: sample object width "
                        f"{parsed.shape[1]} exceeds label_shape width {w} "
                        f"— pass label_shape=(M, {parsed.shape[1]})")
                if parsed.shape[0] > m:
                    raise ValueError(
                        f"ImageDetIter: sample has {parsed.shape[0]} "
                        f"objects but label_shape holds {m} — silently "
                        f"dropping ground truth would corrupt training; "
                        f"pass label_shape=({parsed.shape[0]}, {w})")
                n = parsed.shape[0]
                batch_data[i] = np.transpose(img, (2, 0, 1))
                batch_label[i, :n, :parsed.shape[1]] = parsed[:n]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = bs - i
        return DataBatch(data=[nd_array(batch_data, ctx=self.ctx)],
                        label=[nd_array(batch_label, ctx=self.ctx)], pad=pad)


__all__ += ["ImageDetIter"]
