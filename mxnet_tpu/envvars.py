"""Typed registry of every ``MXNET_TPU_*`` environment variable.

The reference documented its ~80 ``MXNET_*`` knobs in one hand-written
faq page (``docs/faq/env_var.md``) and read them ad hoc all over the C++
tree; the TPU backend grew the same scatter (31 ``MXNET_TPU_*`` reads
across kernels, dist, serving and telemetry) until this module. Now:

- every variable is DECLARED here once — name, type, default, doc,
  subsystem scope — and READ here only: :func:`get` returns the parsed,
  typed value (or the declared default), :func:`get_raw` the raw string.
  ``tools/mxlint``'s ``env-raw-read`` pass forbids raw ``os.environ``
  access to ``MXNET_TPU_*`` names anywhere else in ``mxnet_tpu/``,
  ``tools/`` and ``bench.py``, and its ``env-unregistered`` check
  rejects :func:`get` calls for names not declared here;
- the README "Configuration reference" table is GENERATED from this
  registry (``python -m tools.mxlint --write-envdoc``) and the mxlint
  gate fails when a registered variable is missing from it — the docs
  cannot go stale silently.

This module must stay stdlib-only and import nothing from the package:
``mxnet_tpu/__init__.py`` reads ``MXNET_TPU_MATMUL_PRECISION`` through
it before jax is even configured.

Parsing conventions: ``bool`` treats ``"" / 0 / false / no / off``
(case-insensitive) as False and anything else as True; ``int`` and
``float`` fall back to the declared default on an empty value. A
variable with default ``None`` reads as ``None`` when unset — call
sites own their fallback chain (e.g. the ``DMLC_*`` compat names).
"""
from __future__ import annotations

import os
from collections import OrderedDict

__all__ = ["EnvVar", "ENVVARS", "register", "get", "get_raw", "is_set",
           "all_vars", "markdown_table"]

_FALSY = ("", "0", "false", "no", "off")


class EnvVar:
    """One declared variable: its name, value type (``bool``/``int``/
    ``float``/``str``/``path``), default, one-line doc, and subsystem
    scope (groups the generated reference table)."""

    __slots__ = ("name", "vtype", "default", "doc", "scope")

    def __init__(self, name, vtype, default, doc, scope):
        if not name.startswith("MXNET_TPU_"):
            raise ValueError(f"{name!r} is not an MXNET_TPU_* variable")
        if vtype not in ("bool", "int", "float", "str", "path"):
            raise ValueError(f"unknown env var type {vtype!r}")
        self.name = name
        self.vtype = vtype
        self.default = default
        self.doc = doc
        self.scope = scope

    def parse(self, raw):
        """Raw string → typed value (the declared default when the
        value is empty or unparsable — a typo'd knob must degrade to
        documented behavior, not crash process startup)."""
        if raw is None:
            return self.default
        raw = raw.strip()
        if self.vtype == "bool":
            return raw.lower() not in _FALSY
        if raw == "":
            return self.default
        try:
            if self.vtype == "int":
                return int(raw, 0)
            if self.vtype == "float":
                return float(raw)
        except ValueError:
            return self.default
        return raw      # str / path

    def describe_default(self):
        if self.default is None:
            return "unset"
        if self.vtype == "bool":
            return "on" if self.default else "off"
        return str(self.default)


#: declaration order is documentation order (grouped by scope)
ENVVARS: "OrderedDict[str, EnvVar]" = OrderedDict()


def register(name, vtype, default, doc, scope="runtime"):
    if name in ENVVARS:
        raise ValueError(f"env var {name} registered twice")
    var = EnvVar(name, vtype, default, doc, scope)
    ENVVARS[name] = var
    return var


def get(name):
    """The typed value of a registered variable (its default when
    unset). Raises ``KeyError`` for undeclared names — registering here
    IS the act of creating a configuration knob."""
    return ENVVARS[name].parse(os.environ.get(name))


def get_raw(name):
    """The raw string (None when unset) of a registered variable — for
    fallback chains that must distinguish unset from falsy values."""
    ENVVARS[name]            # undeclared names fail just like get()
    return os.environ.get(name)


def is_set(name):
    ENVVARS[name]
    return name in os.environ


def all_vars():
    return list(ENVVARS.values())


# ---------------------------------------------------------------------------
# the registry — one entry per variable, grouped by subsystem
# ---------------------------------------------------------------------------

# -- core runtime -----------------------------------------------------------
register("MXNET_TPU_SYMBOLIC_JIT", "bool", True,
         "compiled symbolic executor for Module/simple_bind; ``0`` falls "
         "back to the eager per-op DAG walk (bug-bisection ladder)",
         scope="runtime")
register("MXNET_TPU_MATMUL_PRECISION", "str", "high",
         "f32 matmul precision: ``high`` = multi-pass bf16 (~f32 "
         "accuracy), ``default`` = fastest single-pass bf16",
         scope="runtime")
register("MXNET_TPU_CONV_NHWC", "bool", False,
         "execute 2-D convs internally in NHWC (bench knob; measured "
         "±0 — XLA's layout assignment is already optimal)",
         scope="runtime")
register("MXNET_TPU_EMB_GRAD", "str", "plain",
         "embedding-backward lowering: ``plain`` take-VJP scatter, "
         "``sorted`` sort+segment-sum, ``bf16`` bf16-accumulated "
         "scatter (A/B knob; both alternatives measured slower on v5e)",
         scope="runtime")
register("MXNET_TPU_MODEL_STORE", "path", None,
         "model-zoo download/cache root (falls back to "
         "``$MXNET_HOME/models``, then ``~/.mxnet/models``)",
         scope="runtime")

# -- persistent compilation cache -------------------------------------------
register("MXNET_TPU_COMPILE_CACHE", "bool", True,
         "persistent on-disk XLA compilation cache, configured at "
         "CachedOp trace / executor bind time; ``0`` disables — every "
         "process then recompiles every shape from scratch",
         scope="compile_cache")
register("MXNET_TPU_COMPILE_CACHE_DIR", "path", None,
         "persistent compile-cache directory (default "
         "``~/.cache/mxnet_tpu/compile_cache``); share it across "
         "engine processes so restarts reuse each other's executables",
         scope="compile_cache")
register("MXNET_TPU_COMPILE_CACHE_MIN_S", "float", 1.0,
         "only compiles slower than this many seconds are persisted "
         "(``0`` persists everything — tests use it to force "
         "cross-process hits)", scope="compile_cache")
register("MXNET_TPU_WARMUP_MANIFEST", "path", None,
         "warmup-manifest path: the serving router persists the "
         "fleet-union visited-shape manifest here, and a restarting "
         "engine replays it via ``warmup(manifest=...)`` before "
         "admitting traffic", scope="compile_cache")

# -- Pallas kernels ---------------------------------------------------------
register("MXNET_TPU_PALLAS_INTERPRET", "bool", False,
         "run Pallas kernels in interpret mode (off-TPU kernel testing)",
         scope="kernels")
register("MXNET_TPU_DISABLE_PALLAS", "bool", False,
         "force the plain jnp/XLA lowering for every fused-kernel op",
         scope="kernels")
register("MXNET_TPU_FLASH_BLOCK_Q", "int", 512,
         "flash-attention query-tile cap (v5e-measured optimum 512)",
         scope="kernels")
register("MXNET_TPU_FLASH_BLOCK_K", "int", 2048,
         "flash-attention kv-tile cap (effective tile is "
         "``min(seq, cap)``)", scope="kernels")
register("MXNET_TPU_FLASH_SPLIT_BWD", "bool", False,
         "use the two-kernel flash-attention backward instead of the "
         "fused one-pass kernel (A/B + fallback)", scope="kernels")
register("MXNET_TPU_FUSED_LSTM", "bool", False,
         "opt-in whole-sequence Pallas LSTM kernel (XLA's scan measured "
         "faster at WikiText-2 shapes; see BASELINE.md)", scope="kernels")
register("MXNET_TPU_XENT_BLOCK_N", "int", 128,
         "fused softmax-CE kernel row-tile cap", scope="kernels")
register("MXNET_TPU_XENT_BLOCK_V", "int", 2048,
         "fused softmax-CE kernel vocab-tile cap", scope="kernels")

# -- distributed ------------------------------------------------------------
register("MXNET_TPU_COORDINATOR", "str", None,
         "jax.distributed coordinator ``host:port`` (set by "
         "``tools/launch.py``; ``DMLC_PS_ROOT_URI``/``_PORT`` accepted "
         "for script compat)", scope="dist")
register("MXNET_TPU_NUM_PROCS", "int", None,
         "world size for multi-process rendezvous (``DMLC_NUM_WORKER`` "
         "compat fallback)", scope="dist")
register("MXNET_TPU_PROC_ID", "int", None,
         "this process's rank (``DMLC_WORKER_ID`` compat fallback)",
         scope="dist")
register("MXNET_TPU_LOCAL_RANK", "int", 0,
         "rank within this host (set per worker by ``tools/launch.py``; "
         "horovod-shim ``local_rank``)", scope="dist")

# -- serving dispatch wire --------------------------------------------------
register("MXNET_TPU_WIRE", "bool", True,
         "binary dispatch wire: ``ServingEngine.expose()`` starts the "
         "typed-frame dispatch listener next to the HTTP server, and a "
         "``ServingRouter`` upgrades remote seats that advertise a "
         "``wire_port`` to persistent multiplexed connections; ``0`` "
         "keeps dispatch on the HTTP/JSON long-poll only", scope="wire")
register("MXNET_TPU_WIRE_PORT", "int", 0,
         "engine dispatch-listener port (``0`` picks a free port; the "
         "bound port is advertised at ``/healthz`` as ``wire_port``). "
         "A taken configured port falls back to ephemeral with a "
         "``wire_port_fallback`` event", scope="wire")
register("MXNET_TPU_WIRE_CONNS", "int", 2,
         "persistent multiplexed wire connections a router keeps per "
         "wire-capable engine (one reader thread each demuxes replies "
         "by correlation id)", scope="wire")
register("MXNET_TPU_WIRE_TIMEOUT_S", "float", 5.0,
         "wire connect/handshake timeout and the grace added on top "
         "of the dispatch timeout before an unanswered in-flight "
         "request is failed over", scope="wire")
register("MXNET_TPU_WIRE_MAX_FRAME_MB", "int", 256,
         "dispatch-wire frame size cap in MiB (length-bomb guard; a "
         "larger prefix refuses the connection before allocating — "
         "the dist_async channel keeps its own 8 GiB cap)",
         scope="wire")
register("MXNET_TPU_WIRE_HTTP_POOL", "int", 8,
         "bounded waiter threads per remote seat for the HTTP/JSON "
         "fallback dispatch path (the legacy thread-per-in-flight-"
         "request shape could thread-bomb under load spikes)",
         scope="wire")

# -- decode serving: paged KV cache + continuous decode batching ------------
register("MXNET_TPU_KV_PAGE_SIZE", "int", 16,
         "tokens per paged-KV-cache page (``serving/kvcache.py``): the "
         "allocation granule of the decode engine's attention memory; "
         "multiples of 8 keep the page a whole sublane tile on TPU",
         scope="decode")
register("MXNET_TPU_KV_PAGES", "int", 256,
         "paged-KV-cache pool capacity in pages, preallocated per "
         "layer at engine start (+1 internal scratch page); an "
         "exhausted pool defers decode joins instead of failing them",
         scope="decode")
register("MXNET_TPU_DECODE_ROWS", "int", 8,
         "decode-batch slot cap (``DecodeEngine`` default max "
         "concurrent sequences; row counts quantize to powers of two "
         "up to this, one compiled step per (rows, table-width) "
         "bucket)", scope="decode")
register("MXNET_TPU_DECODE_MAX_NEW_TOKENS", "int", 64,
         "default generation cap for decode requests that bring no "
         "``max_new_tokens`` of their own", scope="decode")
register("MXNET_TPU_DECODE_DONATE", "bool", True,
         "thread ``jax.jit(..., donate_argnums=...)`` through the "
         "decode/prefill steps so the KV page pool updates in place "
         "(no per-step cache-sized allocation); ``0`` copies — the "
         "A/B knob for the donation win", scope="decode")
register("MXNET_TPU_DECODE_PREFILLS_PER_ITER", "int", 1,
         "prompt prefills admitted per decode-loop iteration: bounds "
         "how long the running decode batch can stall behind prefill "
         "work (the prefill/decode split-scheduling knob)",
         scope="decode")
register("MXNET_TPU_DECODE_PREFILL_BUDGET", "int", 64,
         "prompt tokens prefilled per decode-loop iteration: prompts "
         "are split into kernel-sized chunks interleaved at iteration "
         "boundaries, so a long prompt never stalls the running batch "
         "for more than one chunk; ``0`` restores whole-prompt dense "
         "prefill (the chunked-prefill A/B baseline)", scope="decode")
register("MXNET_TPU_KV_PREFIX", "bool", True,
         "prefix KV cache reuse (``serving/kvcache.py``): prompts "
         "sharing a token prefix share its full KV pages read-only "
         "(refcounted, copy-on-write on divergence); ``0`` disables — "
         "the prefix-reuse A/B knob. Needs chunked prefill "
         "(``MXNET_TPU_DECODE_PREFILL_BUDGET`` > 0) to take effect",
         scope="decode")
register("MXNET_TPU_KV_PREFIX_PAGES", "int", 64,
         "bounded LRU capacity of the prefix-KV index, in entries "
         "(one full page each); eviction unpins the page, which "
         "recycles once no live sequence references it",
         scope="decode")
register("MXNET_TPU_DECODE_TEMPERATURE", "float", 0.0,
         "default decode sampling temperature for requests that bring "
         "none: ``0`` is greedy argmax — deterministic by "
         "construction, the byte-reproducible solo-parity lever",
         scope="decode")
register("MXNET_TPU_DECODE_TOP_K", "int", 0,
         "default top-k sampling cutoff for decode requests (``0`` = "
         "no top-k truncation; only applies when temperature > 0)",
         scope="decode")
register("MXNET_TPU_DECODE_TOP_P", "float", 1.0,
         "default nucleus (top-p) sampling mass for decode requests "
         "(``1.0`` = no truncation; only applies when temperature "
         "> 0)", scope="decode")
register("MXNET_TPU_SLO_INTER_TOKEN_MS", "float", 250.0,
         "decode inter-token latency bound for the default "
         "``decode_inter_token`` LatencySLO (p-target reuses "
         "``MXNET_TPU_SLO_LATENCY_TARGET``)", scope="slo")

# -- telemetry: events / spans ----------------------------------------------
register("MXNET_TPU_EVENT_LOG", "path", None,
         "structured JSONL run-event log path (a directory gets one "
         "``events-<pid>.jsonl`` per process)", scope="telemetry")
register("MXNET_TPU_EVENT_LOG_MAX_MB", "float", None,
         "rotate the event log at this size (MB); unset = no rotation",
         scope="telemetry")
register("MXNET_TPU_EVENT_LOG_KEEP", "int", 3,
         "rotated event-log files kept (``read_events`` reads across "
         "rotations)", scope="telemetry")
register("MXNET_TPU_SPANS", "bool", True,
         "span recording (tail-sampled request tracing); ``0`` disables "
         "— the ring is bounded either way", scope="telemetry")
register("MXNET_TPU_TRACE_SLOW_MS", "float", 250.0,
         "tail-sampling keep threshold: traces whose local root ran "
         "longer are kept in full", scope="telemetry")
register("MXNET_TPU_TRACE_BUFFER", "int", 64,
         "kept-trace ring size", scope="telemetry")
register("MXNET_TPU_TRACE_MAX_SPANS", "int", 256,
         "per-trace span cap (a leaked trace cannot grow the process)",
         scope="telemetry")
register("MXNET_TPU_TRACE_MAX_ACTIVE", "int", 256,
         "in-flight (not yet sampled) trace buffer cap",
         scope="telemetry")
register("MXNET_TPU_ATTRIBUTION", "bool", True,
         "per-request critical-path stage attribution (stage spans, "
         "``InferenceFuture.breakdown``, the ``/whyslow`` aggregator); "
         "``0`` — or spans off — disables: no stamps, no families, no "
         "threads", scope="telemetry")
register("MXNET_TPU_ATTRIBUTION_WINDOW", "int", 2048,
         "per-stage sample window behind the ``/whyslow`` windowed "
         "p99 (per (stage, tenant_class, model) cell)",
         scope="telemetry")
register("MXNET_TPU_ATTRIBUTION_TOP", "int", 3,
         "stages ranked in ``/whyslow``'s ``top`` table and attached "
         "to firing latency alert payloads", scope="telemetry")

# -- telemetry: continuous profiler / resource accounting -------------------
register("MXNET_TPU_PROF", "bool", True,
         "always-on continuous sampling profiler daemon (Google-Wide-"
         "Profiling style): started by serving engines/routers and "
         "bench legs, samples every thread's Python stack into bounded "
         "folded-stack counts served at ``/profile``; ``0`` disables",
         scope="telemetry")
register("MXNET_TPU_PROF_HZ", "float", 19.0,
         "continuous-profiler sampling rate (Hz); the odd default "
         "avoids phase-locking with 1 s/100 ms periodic work",
         scope="telemetry")
register("MXNET_TPU_PROF_MAX_STACKS", "int", 2048,
         "distinct (thread, folded-stack) entries kept by the "
         "continuous profiler; overflow folds into a per-thread "
         "``(stack-table-full)`` bucket so totals stay honest",
         scope="telemetry")
register("MXNET_TPU_PROF_MAX_DEPTH", "int", 48,
         "frames kept per sampled stack (deepest callees win)",
         scope="telemetry")
register("MXNET_TPU_PROF_RESOURCE_S", "float", 1.0,
         "period of the resource-gauge sweep (host RSS/fds/threads + "
         "device memory) the profiler daemon runs between stack "
         "samples", scope="telemetry")

# -- telemetry: flight recorder / watchdog ----------------------------------
register("MXNET_TPU_FLIGHT_DIR", "path", None,
         "flight-recorder bundle directory (default "
         "``./mxnet_tpu_flight``)", scope="telemetry")
register("MXNET_TPU_WATCHDOG", "bool", True,
         "the stall-watchdog daemon thread; ``0`` disables",
         scope="telemetry")
register("MXNET_TPU_WATCHDOG_INTERVAL_S", "float", 5.0,
         "watchdog probe poll period (seconds)", scope="telemetry")
register("MXNET_TPU_WATCHDOG_STALL_S", "float", 30.0,
         "shared stall threshold watchdog probes compare against "
         "(seconds)", scope="telemetry")
register("MXNET_TPU_WATCHDOG_COMPILE_GRACE_S", "float", 300.0,
         "extra stall allowance while a serving engine has a "
         "first-visit trace+compile window open — first-visit "
         "compiles must not trip flight-recorder bundles",
         scope="telemetry")

# -- SLOs / alerting --------------------------------------------------------
register("MXNET_TPU_SLO", "bool", True,
         "in-process SLO engine: serving engines/routers register "
         "their default objectives (latency quantile, availability, "
         "cost budget, engine-up fraction) and the alert daemon "
         "evaluates multi-window burn-rate / threshold / absence "
         "rules against them; ``0`` disables evaluation, exemplar "
         "recording and the ``/alerts``+``/slo`` endpoints",
         scope="slo")
register("MXNET_TPU_SLO_EVAL_S", "float", 5.0,
         "alert-daemon evaluation period (seconds)", scope="slo")
register("MXNET_TPU_SLO_WINDOW_SCALE", "float", 1.0,
         "multiplier on every SLO window (burn-rate long/short "
         "windows, pending durations, error-budget window) — drills "
         "and tests shrink hours to seconds with one knob",
         scope="slo")
register("MXNET_TPU_SLO_BUDGET_S", "float", 2592000.0,
         "error-budget accounting window in seconds (default 30 "
         "days; clipped to process uptime)", scope="slo")
register("MXNET_TPU_SLO_LATENCY_MS", "float", 1000.0,
         "default serving latency objective: requests must complete "
         "under this many milliseconds (snapped up to the nearest "
         "histogram bucket boundary)", scope="slo")
register("MXNET_TPU_SLO_LATENCY_TARGET", "float", 0.99,
         "fraction of requests that must meet the latency objective "
         "(the quantile, as a ratio target)", scope="slo")
register("MXNET_TPU_SLO_AVAILABILITY_TARGET", "float", 0.999,
         "availability objective: fraction of requests that must "
         "complete (not shed, not errored, not expired)", scope="slo")
register("MXNET_TPU_SLO_COST_S_PER_1K", "float", None,
         "cost objective: device seconds per 1k valid tokens budget "
         "(unset = cost objective off; set it from a measured "
         "baseline)", scope="slo")
register("MXNET_TPU_SLO_ENGINE_UP_FRACTION", "float", 0.5,
         "router fleet objective: alert when fewer than this "
         "fraction of registered engines is routable", scope="slo")
register("MXNET_TPU_SLO_EXEMPLARS", "bool", True,
         "record (latency bucket, trace_id) exemplar pairs on the "
         "serving/router total-latency histograms, rendered "
         "OpenMetrics-style in the text exposition and surfaced on "
         "``/alerts``; ``0`` skips the per-request exemplar write",
         scope="slo")
register("MXNET_TPU_ALERT_RESOLVED_KEEP_S", "float", 300.0,
         "how long a resolved alert stays listed on ``/alerts`` "
         "before decaying to inactive", scope="slo")
register("MXNET_TPU_ALERT_HISTORY", "int", 128,
         "alert state-transition history ring size (served on "
         "``/alerts``, carried into flight bundles)", scope="slo")

# -- synthetic canaries -----------------------------------------------------
register("MXNET_TPU_CANARY", "bool", True,
         "black-box canary prober: a router-side daemon submits "
         "synthetic golden requests to every seat from outside (over "
         "the binary wire and the HTTP dispatch path, round-robined), "
         "checks responses against the golden checksum, and feeds the "
         "per-seat canary-absence page rule; ``0`` spawns no thread "
         "and registers no ``mxnet_tpu_canary_*`` families",
         scope="canary")
register("MXNET_TPU_CANARY_INTERVAL_S", "float", 1.0,
         "canary probe round period (seconds between rounds; every "
         "seat is probed once per round)", scope="canary")
register("MXNET_TPU_CANARY_TIMEOUT_S", "float", 10.0,
         "per-probe completion timeout: a probe still unanswered after "
         "this long counts ``timeout`` (a wedged seat answers nothing "
         "— exactly what the absence rule pages on)", scope="canary")
register("MXNET_TPU_CANARY_ABSENCE_S", "float", 300.0,
         "canary-absence window in pre-scale seconds: no successful "
         "canary against a seat for this long (scaled by "
         "``MXNET_TPU_SLO_WINDOW_SCALE``) pages even when the seat "
         "self-reports healthy", scope="canary")

# -- SLO-aware routing ------------------------------------------------------
register("MXNET_TPU_ROUTER_WEIGHTS", "bool", True,
         "SLO-aware routing weights: the router's health poll folds "
         "per-seat burn rate (``/slo``), windowed device-s/1k-tokens "
         "drift and canary latency into a smoothed per-seat weight "
         "the least-outstanding picker divides by — a seat burning "
         "its error budget sheds traffic smoothly, with hysteresis; "
         "``0`` pins every weight at 1.0 (classic least-outstanding)",
         scope="routing")
register("MXNET_TPU_ROUTER_WEIGHT_FLOOR", "float", 0.05,
         "minimum routing weight for a degraded seat — a trickle of "
         "traffic keeps flowing so recovery is observable (0.05 = "
         "one twentieth of a full share)", scope="routing")
register("MXNET_TPU_ROUTER_WEIGHT_GAIN", "float", 0.4,
         "per-poll smoothing gain toward the weight target (1.0 = "
         "jump immediately, small = glacial)", scope="routing")

# -- multi-tenant, multi-model serving --------------------------------------
register("MXNET_TPU_TENANT_WEIGHTS", "str", None,
         "WFQ admission-class weights as ``class:weight`` pairs "
         "(overlays the 4/2/1 default, e.g. "
         "``priority:8,best-effort:1``): the queue dequeues classes "
         "in proportion to weight under contention", scope="tenancy")
register("MXNET_TPU_TENANT_DEPTH_SHARES", "str", None,
         "per-class admission-queue depth budgets as fractions of "
         "``max_depth`` (``class:share`` pairs, default 1.0 each — "
         "e.g. ``best-effort:0.5`` caps best-effort at half the "
         "queue even before WFQ eviction kicks in)", scope="tenancy")
register("MXNET_TPU_TENANT_DEADLINE_MS", "str", None,
         "per-class DEFAULT deadlines (ms) for requests that bring "
         "none (``class:ms`` pairs, e.g. ``best-effort:2000``): "
         "under overload, expiry consumes the short-deadline classes "
         "first", scope="tenancy")
register("MXNET_TPU_TENANT_SLO_MS", "str", None,
         "per-class total-latency SLO thresholds (ms) for the "
         "``default_tenant_objectives`` set (``class:ms`` pairs; "
         "classes not listed default to 0.5x / 1x / 4x the serving "
         "latency bound for priority/standard/best-effort)",
         scope="tenancy")
register("MXNET_TPU_MODEL_DEFAULT", "str", "default",
         "model id a single-model engine registers under and a "
         "model-less submit targets — the backward-compat identity "
         "of the pre-registry fleet", scope="tenancy")

# -- router active/active HA ------------------------------------------------
register("MXNET_TPU_ROUTER_HA", "bool", True,
         "router active/active HA: with a peer configured, every "
         "admitted request is journaled (correlation id + payload) "
         "to the peer over the wire before dispatch, and a dead "
         "router's survivor adopts the orphaned in-flight requests "
         "front-of-queue; ``0`` disables journaling and the HA "
         "listener entirely", scope="ha")
register("MXNET_TPU_ROUTER_HA_PEER", "str", None,
         "the PEER router's exposition base URL (e.g. "
         "``http://host:9200``): liveness is polled off its "
         "``/healthz`` (which advertises ``ha_port``) and the journal "
         "link connects to that port", scope="ha")
register("MXNET_TPU_ROUTER_HA_PORT", "int", 0,
         "this router's HA journal-listener port (``0`` picks a free "
         "port, advertised at ``/healthz`` as ``ha_port``); setting "
         "it non-zero also starts the listener without a configured "
         "outbound peer (asymmetric HA)", scope="ha")
register("MXNET_TPU_ROUTER_HA_JOURNAL", "int", 4096,
         "peer-journal capacity (in-flight requests held for the "
         "peer); past it the OLDEST entry is dropped (counted "
         "``journal_drop``)", scope="ha")
register("MXNET_TPU_ROUTER_HA_ACK_S", "float", 1.0,
         "bounded wait for the peer's journal ack before a request "
         "becomes dispatchable (the durability cost of zero-loss); "
         "an ack miss degrades that request to unjournaled",
         scope="ha")

# -- autoscaler -------------------------------------------------------------
register("MXNET_TPU_AUTOSCALE", "bool", True,
         "fleet autoscaler enable gate: a constructed "
         "``FleetAutoscaler`` spawns/retires engine seats from "
         "sustained burn rate + queue depth and replaces dead seats "
         "with manifest-warmed engines; ``0`` makes ``start()`` a "
         "no-op (no thread)", scope="autoscale")
register("MXNET_TPU_AUTOSCALE_MIN", "int", 1,
         "minimum seats the autoscaler keeps (scale-down floor)",
         scope="autoscale")
register("MXNET_TPU_AUTOSCALE_MAX", "int", 4,
         "maximum seats the autoscaler grows to (scale-up ceiling)",
         scope="autoscale")
register("MXNET_TPU_AUTOSCALE_INTERVAL_S", "float", 1.0,
         "autoscaler evaluation period (seconds)", scope="autoscale")
register("MXNET_TPU_AUTOSCALE_BURN", "float", 6.0,
         "fleet short-window burn-rate threshold that (sustained) "
         "triggers a scale-up (6x = the SRE ticket factor)",
         scope="autoscale")
register("MXNET_TPU_AUTOSCALE_QUEUE", "int", 64,
         "router queue depth that (sustained) triggers a scale-up",
         scope="autoscale")
register("MXNET_TPU_AUTOSCALE_HOLD_S", "float", 5.0,
         "how long a scale-up signal must hold before acting (a "
         "burst must not buy a seat)", scope="autoscale")
register("MXNET_TPU_AUTOSCALE_COOLDOWN_S", "float", 30.0,
         "minimum seconds between autoscaler actions (replacement of "
         "a DEAD seat is exempt — availability does not wait out a "
         "cooldown)", scope="autoscale")
register("MXNET_TPU_AUTOSCALE_IDLE_S", "float", 120.0,
         "how long the fleet must stay idle (empty queue, burn under "
         "1x) before an autoscaler-added seat is retired",
         scope="autoscale")
register("MXNET_TPU_AUTOSCALE_REPLACE_S", "float", 3.0,
         "how long a seat must stay unroutable before the autoscaler "
         "replaces it (debounces a transient health blip)",
         scope="autoscale")

# -- chaos injection --------------------------------------------------------
register("MXNET_TPU_CHAOS", "bool", False,
         "deterministic fault-injection harness: engines/routers "
         "register with the process chaos controller at start and "
         "the scripted schedule (``MXNET_TPU_CHAOS_SCHEDULE``) "
         "injects faults — slowed/wedged forwards, killed wire "
         "connections, dropped/delayed dispatch frames, killed "
         "engine/router processes; ``0`` (the default) patches "
         "NOTHING and spawns no thread", scope="chaos")
register("MXNET_TPU_CHAOS_SEED", "int", 0,
         "chaos rng seed: the same seed + schedule replays an "
         "identical fault sequence (the determinism contract)",
         scope="chaos")
register("MXNET_TPU_CHAOS_SCHEDULE", "str", None,
         "the fault schedule: inline JSON (a list of "
         "``{at, fault, target, ...}`` entries) or a path to a JSON "
         "file; unset = an armed controller with no scripted faults "
         "(drills drive it programmatically)", scope="chaos")

# -- alert egress -----------------------------------------------------------
register("MXNET_TPU_ALERT_EGRESS", "bool", True,
         "alert delivery out of the process: alert daemons attach the "
         "process notifier (webhook/file/stdout sinks, retry + "
         "dead-letter spool) when any sink is configured; ``0`` spawns "
         "no thread and registers no ``mxnet_tpu_alert_egress_*`` "
         "families", scope="egress")
register("MXNET_TPU_ALERT_EGRESS_URL", "str", None,
         "webhook sink: alert notifications POST here as JSON (unset "
         "= no webhook sink)", scope="egress")
register("MXNET_TPU_ALERT_EGRESS_FILE", "path", None,
         "file sink: alert notifications append here as JSONL (tests "
         "and air-gapped runs page into a file)", scope="egress")
register("MXNET_TPU_ALERT_EGRESS_STDOUT", "bool", False,
         "stdout sink: print alert notifications as JSON lines",
         scope="egress")
register("MXNET_TPU_ALERT_EGRESS_RETRIES", "int", 4,
         "delivery attempts per sink before a notification goes to "
         "the dead-letter spool (exponential backoff + jitter between "
         "attempts)", scope="egress")
register("MXNET_TPU_ALERT_EGRESS_BACKOFF_S", "float", 0.5,
         "base delivery backoff in seconds (doubles per retry, plus "
         "up to 50% jitter)", scope="egress")
register("MXNET_TPU_ALERT_EGRESS_SPOOL", "path", None,
         "dead-letter spool directory for undeliverable notifications "
         "(default ``<MXNET_TPU_FLIGHT_DIR>/egress-spool``); replayed "
         "on the next notifier start so a page survives process death",
         scope="egress")
register("MXNET_TPU_ALERT_EGRESS_SPOOL_MAX", "int", 256,
         "dead-letter spool bound (files); past it the OLDEST spooled "
         "notification is dropped to keep the newest pages",
         scope="egress")

# -- incident timeline ------------------------------------------------------
register("MXNET_TPU_INCIDENT_GAP_S", "float", 120.0,
         "incident correlation gap in pre-scale seconds (scaled by "
         "``MXNET_TPU_SLO_WINDOW_SCALE``): signals this close fold "
         "into one incident, and a quiet incident with nothing firing "
         "and no seat down closes after it", scope="incidents")

# -- retrospective history --------------------------------------------------
register("MXNET_TPU_HISTORY", "bool", True,
         "retrospective time-series history: engines/routers run a "
         "scraper daemon sampling their exposition into a bounded "
         "store served at ``/query_range`` + ``/series`` and frozen "
         "into flight bundles on incident open; ``0`` disables the "
         "whole subsystem (no thread, no store)", scope="history")
register("MXNET_TPU_HISTORY_DIR", "path", None,
         "persist history segments under this directory (append-only "
         "JSONL segment files per family and tier, reloaded on the "
         "next start); unset keeps the store in-memory only — same "
         "bounds, no disk", scope="history")
register("MXNET_TPU_HISTORY_RETAIN_S", "float", 86400.0,
         "retention of the coarsest (60 s) downsampling tier in "
         "seconds; the raw and 10 s tiers retain proportionally "
         "shorter windows", scope="history")
register("MXNET_TPU_HISTORY_MAX_MB", "float", 64.0,
         "on-disk budget for ``MXNET_TPU_HISTORY_DIR`` (MB); past it "
         "the oldest segment files are deleted, finest tier first",
         scope="history")
register("MXNET_TPU_HISTORY_SCRAPE_S", "float", 5.0,
         "history scraper sampling interval in seconds (engines "
         "sample the process registry, routers the fleet-merged "
         "exposition)", scope="history")
register("MXNET_TPU_HISTORY_SEGMENT_MB", "float", 4.0,
         "history segment rotation size (MB): the active append-only "
         "segment file rotates past it, so retention/budget deletes "
         "operate on whole sealed segments", scope="history")

# -- traffic capture & shadow validation ------------------------------------
register("MXNET_TPU_CAPTURE", "bool", False,
         "sampled production-traffic capture: engines record a "
         "head-sampled fraction of admitted requests (prompt, "
         "sampling params + seed, model/tenant identity, outcome, "
         "output digest, latency + stage breakdown) into a bounded "
         "crash-safe corpus for deterministic replay; canary traffic "
         "is excluded; ``0`` (the default) builds nothing — no "
         "thread, no ``mxnet_tpu_capture_*`` families, no files",
         scope="capture")
register("MXNET_TPU_CAPTURE_DIR", "path", None,
         "persist the capture corpus under this directory "
         "(length+CRC-framed wire-codec segment files, rotated and "
         "reloadable across processes); unset keeps the corpus "
         "in-memory only — same byte bound, no disk", scope="capture")
register("MXNET_TPU_CAPTURE_RATE", "float", 1.0,
         "head-sampling rate in 0..1: the fraction of admitted "
         "non-synthetic requests recorded, by exact deterministic "
         "credit accumulation (0.25 records every 4th request)",
         scope="capture")
register("MXNET_TPU_CAPTURE_MAX_MB", "float", 64.0,
         "corpus byte budget (MB); past it the oldest SEALED segments "
         "are evicted (the active segment keeps writing) — the "
         "history-store discipline", scope="capture")
register("MXNET_TPU_CAPTURE_PAYLOAD", "str", "tokens",
         "what the record keeps of the prompt: ``tokens`` (the int32 "
         "token array — the corpus is replayable) or ``digest`` "
         "(only its digest — privacy mode; replay skips such records "
         "and counts them)", scope="capture")
register("MXNET_TPU_SHADOW", "bool", False,
         "shadow-diff validation: the router mirrors a fraction of "
         "completed live requests at a candidate seat "
         "(fire-and-forget — live futures never wait on the shadow), "
         "diffs output digests + latency, and exposes the "
         "``/shadow`` verdict the ``swap_model`` gate consults; "
         "``0`` (the default) builds nothing — no mirror branch, no "
         "``mxnet_tpu_shadow_*`` families", scope="capture")
register("MXNET_TPU_SHADOW_FRACTION", "float", 0.25,
         "fraction of completed non-synthetic live requests mirrored "
         "at the shadow seat (deterministic credit accumulation, "
         "like the capture sampler)", scope="capture")
register("MXNET_TPU_SHADOW_THRESHOLD", "float", 0.0,
         "maximum tolerated shadow divergence rate: the swap gate "
         "refuses the flip while ``divergences/compared`` exceeds "
         "this (0.0 = any divergence blocks — the seeded-decode "
         "byte-identical contract)", scope="capture")
register("MXNET_TPU_SHADOW_MIN_REQUESTS", "int", 16,
         "comparisons required before the shadow verdict may pass: "
         "the gate refuses the flip until this many mirrored "
         "requests have been diffed (a candidate must earn the "
         "swap)", scope="capture")
register("MXNET_TPU_SHADOW_TIMEOUT_S", "float", 30.0,
         "per-mirrored-request timeout on the shadow leg (a wedged "
         "candidate counts as an error, never blocks anything)",
         scope="capture")

# -- concurrency sanitizer --------------------------------------------------
register("MXNET_TPU_SANITIZE", "bool", False,
         "runtime concurrency sanitizer: patches ``threading.Lock``/"
         "``RLock``/``Condition`` (repo-created only) with wrappers "
         "that maintain the observed lock-order graph (cycle = "
         "potential deadlock, flagged even when the fatal "
         "interleaving never fires), time contended holds, and track "
         "thread lifecycles; the pytest plugin fails the session on "
         "unbaselined findings (``tests/mxsan_baseline.json``, "
         "``# mxsan: allow=<rule>`` suppressions). Off = nothing is "
         "patched", scope="sanitize")
register("MXNET_TPU_SANITIZE_HOLD_MS", "float", 100.0,
         "sanitizer long-hold threshold: a lock held longer than this "
         "many milliseconds WHILE another thread waits on it is "
         "reported (``long-hold``) — the convoy shape, not mere "
         "slowness", scope="sanitize")

# -- bench ------------------------------------------------------------------
register("MXNET_TPU_PEAK_TFLOPS", "float", None,
         "override the per-chip peak dense bf16 TFLOP/s used for "
         "bench.py MFU (unset = inferred from device kind)",
         scope="bench")
register("MXNET_TPU_PEAK_HBM_GBPS", "float", None,
         "override the per-chip peak HBM bandwidth GB/s used for "
         "bench.py roofline fields", scope="bench")

# -- tests / dev harness ----------------------------------------------------
register("MXNET_TPU_TEST_REAL_DEVICE", "bool", False,
         "run the test suite against the real backend instead of the "
         "virtual 8-device CPU mesh", scope="tests")
register("MXNET_TPU_NIGHTLY", "bool", False,
         "enable the large-tensor nightly test tier (>2^31-element "
         "allocations)", scope="tests")
register("MXNET_TPU_DRYRUN_REAL", "bool", False,
         "``dryrun_multichip`` uses real devices instead of a forced "
         "CPU mesh", scope="tests")


_SCOPE_TITLES = OrderedDict([
    ("runtime", "Core runtime"),
    ("compile_cache", "Persistent compilation cache"),
    ("kernels", "Pallas kernels"),
    ("dist", "Distributed"),
    ("wire", "Serving dispatch wire"),
    ("decode", "Decode serving (paged KV cache + continuous batching)"),
    ("telemetry", "Telemetry / observability"),
    ("slo", "SLOs & alerting"),
    ("routing", "SLO-aware routing"),
    ("tenancy", "Multi-tenant, multi-model serving"),
    ("ha", "Router active/active HA"),
    ("autoscale", "Autoscaler"),
    ("chaos", "Chaos injection"),
    ("canary", "Synthetic canaries"),
    ("egress", "Alert egress"),
    ("incidents", "Incident timeline"),
    ("history", "Retrospective history"),
    ("capture", "Traffic capture & shadow validation"),
    ("sanitize", "Concurrency sanitizer"),
    ("bench", "Benchmarks"),
    ("tests", "Tests / dev harness"),
])


def markdown_table():
    """The generated README "Configuration reference" body: one table
    per scope, every registered variable present exactly once."""
    lines = []
    for scope, title in _SCOPE_TITLES.items():
        rows = [v for v in ENVVARS.values() if v.scope == scope]
        if not rows:
            continue
        lines.append(f"**{title}**")
        lines.append("")
        lines.append("| Variable | Type | Default | Effect |")
        lines.append("|---|---|---|---|")
        for v in rows:
            lines.append(f"| `{v.name}` | {v.vtype} | "
                         f"`{v.describe_default()}` | {v.doc} |")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"
