"""Test utilities (python/mxnet/test_utils.py analog).

The reference's testing backbone, preserved because SURVEY §4 calls it
the gate for everything else:

- ``assert_almost_equal`` with per-dtype default tolerances (extended
  with bfloat16 — the TPU-native half type);
- ``check_numeric_gradient`` — central finite differences vs autograd;
- ``check_consistency`` — run the same computation under several
  contexts/dtypes and compare forward/backward. On this backend the
  pair is cpu-f32 vs tpu-f32/bf16 (the cpu↔gpu golden harness of
  tests/python/gpu/test_operator_gpu.py);
- ``default_context``, ``with_seed``/``@with_seed()`` determinism.
"""
from __future__ import annotations

import functools
import logging
import os
import random as pyrandom

import numpy as np

from .base import dtype_name
from .context import Context, cpu, current_context
from .ndarray import NDArray, array
from . import random as mx_random

__all__ = [
    "default_context", "set_default_context", "default_dtype",
    "assert_almost_equal", "almost_equal", "same", "rand_ndarray",
    "rand_shape_nd", "check_numeric_gradient", "check_consistency",
    "with_seed", "simple_forward", "list_gpus", "download",
]

_DEFAULT_CTX = None

# per-dtype (rtol, atol) — reference test_utils tolerance tables + bf16
_TOLS = {
    "float16": (1e-2, 1e-4),
    "bfloat16": (3e-2, 1e-3),
    "float32": (1e-4, 1e-6),
    "float64": (1e-5, 1e-8),
}

# Per-DEVICE tolerance widening (the reference's check_consistency keys
# tolerances on (device, dtype) for the same reason): on TPU, float32
# matmuls execute as bf16 MXU passes and transcendentals are polynomial
# approximations, so f32 results carry ~1e-3 relative error vs CPU.
_TPU_TOLS = {
    "float32": (5e-3, 2e-3),
    "float64": (5e-3, 2e-3),
}


_ON_TPU_CACHE = None


def _on_tpu():
    """LAZY backend probe: jax.default_backend() initializes the XLA
    backend, which must never happen at mxnet_tpu import time
    (jax.distributed.initialize has to come first in dist workers)."""
    global _ON_TPU_CACHE
    if _ON_TPU_CACHE is None:
        try:
            import jax
            # tpu/axon only: the widened tolerances exist because f32
            # rides multi-pass bf16 MXU matmuls — a rationale that does
            # not hold on gpu, where true-f32 accuracy is expected
            _ON_TPU_CACHE = jax.default_backend() in ("tpu", "axon")
        except Exception:
            _ON_TPU_CACHE = False
    return _ON_TPU_CACHE


def device_tols(dtype="float32"):
    """(rtol, atol) for comparing `dtype` results on the active backend
    — use in tests that call numpy asserts directly."""
    if _on_tpu() and str(dtype) in _TPU_TOLS:
        return _TPU_TOLS[str(dtype)]
    return _TOLS.get(str(dtype), (1e-4, 1e-6))


def default_context() -> Context:
    return _DEFAULT_CTX or current_context()


def set_default_context(ctx: Context):
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def default_dtype():
    return np.float32


def _to_np(a):
    return a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)


def same(a, b):
    return np.array_equal(_to_np(a), _to_np(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a, b = _to_np(a), _to_np(b)
    rtol, atol = _resolve_tols(a, b, rtol, atol)
    return np.allclose(a.astype(np.float64), b.astype(np.float64),
                       rtol=rtol, atol=atol, equal_nan=equal_nan)


def _resolve_tols(a, b, rtol, atol):
    if rtol is None or atol is None:
        names = {str(a.dtype), str(b.dtype)}
        worst = (1e-5, 1e-8)
        for nm in names:
            t = _TPU_TOLS.get(nm) if _on_tpu() else None
            t = t or _TOLS.get(nm, (1e-4, 1e-6))
            worst = (max(worst[0], t[0]), max(worst[1], t[1]))
        rtol = worst[0] if rtol is None else rtol
        atol = worst[1] if atol is None else atol
    return rtol, atol


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    a_np, b_np = _to_np(a), _to_np(b)
    rtol, atol = _resolve_tols(a_np, b_np, rtol, atol)
    if not np.allclose(a_np.astype(np.float64), b_np.astype(np.float64),
                       rtol=rtol, atol=atol, equal_nan=equal_nan):
        err = np.abs(a_np.astype(np.float64) - b_np.astype(np.float64))
        rel = err / (np.abs(b_np.astype(np.float64)) + atol)
        raise AssertionError(
            f"{names[0]} and {names[1]} differ: max abs err {err.max():g}, "
            f"max rel err {rel.max():g} (rtol={rtol} atol={atol})\n"
            f"{names[0]}: {a_np}\n{names[1]}: {b_np}")


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    ctx = ctx or default_context()
    arr = np.random.uniform(-1.0, 1.0, size=shape).astype(dtype or np.float32)
    if stype == "default":
        return array(arr, ctx=ctx)
    from .ndarray import sparse
    if density is not None:
        mask = np.random.uniform(size=shape[:1]) < density
        arr = arr * mask.reshape((-1,) + (1,) * (len(shape) - 1))
    return sparse.cast_storage(array(arr, ctx=ctx), stype)


def list_gpus():
    from .context import num_gpus
    return list(range(num_gpus()))


def simple_forward(fn, *inputs, ctx=None, **params):
    ctx = ctx or default_context()
    nd_inputs = [array(x, ctx=ctx) if not isinstance(x, NDArray) else x
                 for x in inputs]
    out = fn(*nd_inputs, **params)
    return out.asnumpy() if isinstance(out, NDArray) else [o.asnumpy() for o in out]


def check_numeric_gradient(fn, inputs, grad_outputs=None, eps=1e-3,
                           rtol=None, atol=None, ctx=None, dtype=np.float64):
    """Central finite differences vs autograd.

    fn: callable(*NDArrays) -> NDArray (scalar or any shape; reduced by
    sum for the check). inputs: list of numpy arrays.

    On an accelerator the DEFAULT tolerances widen (reference:
    per-device tol tables) — finite differences amplify the backend's
    f32 rounding. Explicitly passed rtol/atol are authoritative on every
    backend (callers pinning exact gradients can opt out)."""
    if rtol is None:
        rtol = 5e-2 if _on_tpu() else 1e-2
    if atol is None:
        atol = 5e-3 if _on_tpu() else 1e-3
    from . import autograd

    ctx = ctx or default_context()
    nd_inputs = [array(x.astype(np.float32), ctx=ctx) for x in inputs]
    for x in nd_inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*nd_inputs)
        loss = out.sum()
    loss.backward()
    analytic = [x.grad.asnumpy().astype(np.float64) for x in nd_inputs]

    for i, x in enumerate(inputs):
        numeric = np.zeros_like(x, dtype=np.float64)
        flat = x.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            plus = float(fn(*[array(v.astype(np.float32), ctx=ctx) for v in inputs])
                         .sum().asscalar())
            flat[j] = orig - eps
            minus = float(fn(*[array(v.astype(np.float32), ctx=ctx) for v in inputs])
                          .sum().asscalar())
            flat[j] = orig
            numeric.reshape(-1)[j] = (plus - minus) / (2 * eps)
        assert_almost_equal(analytic[i], numeric, rtol=rtol, atol=atol,
                            names=(f"analytic[{i}]", f"numeric[{i}]"))


def check_consistency(fn, ctx_list, inputs, rtol=None, atol=None,
                      grad_check=True):
    """Run fn under several (ctx, dtype) combos and compare forward and
    backward results — the cpu↔tpu golden harness.

    ctx_list: list of dicts {"ctx": Context, "dtype": str}.
    inputs: list of numpy arrays (cast per-combo).
    """
    from . import autograd

    results = []
    for combo in ctx_list:
        ctx, dt = combo["ctx"], combo.get("dtype", "float32")
        nd_inputs = [array(x, ctx=ctx, dtype=dt) for x in inputs]
        for x in nd_inputs:
            x.attach_grad()
        with autograd.record():
            out = fn(*nd_inputs)
            loss = out.sum()
        if grad_check:
            loss.backward()
            grads = [x.grad.asnumpy().astype(np.float64) for x in nd_inputs]
        else:
            grads = None
        results.append((out.asnumpy().astype(np.float64), grads, combo))

    ref_out, ref_grads, ref_combo = results[0]
    for out, grads, combo in results[1:]:
        dt = combo.get("dtype", "float32")
        t = device_tols(dt)  # per-(device, dtype) — the harness's point
        r = rtol if rtol is not None else t[0]
        a = atol if atol is not None else t[1]
        assert_almost_equal(out, ref_out, rtol=r, atol=a,
                            names=(str(combo), str(ref_combo)))
        if grad_check and grads is not None:
            for g, rg in zip(grads, ref_grads):
                assert_almost_equal(g, rg, rtol=r, atol=a,
                                    names=(f"grad@{combo}", f"grad@{ref_combo}"))
    return results


def with_seed(seed=None):
    """Decorator: seed mxnet+numpy per test, log seed on failure."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            this_seed = seed if seed is not None else np.random.randint(0, 2**31)
            np.random.seed(this_seed)
            mx_random.seed(this_seed)
            pyrandom.seed(this_seed)
            try:
                return fn(*args, **kwargs)
            except Exception:
                logging.error("test failed with seed %d — reproduce with "
                              "@with_seed(%d)", this_seed, this_seed)
                raise
        return wrapper
    return deco


def download(url, fname=None, dirname=None, overwrite=False):
    raise NotImplementedError(
        "network access is unavailable in the TPU sandbox; place files locally")
