"""``mx.np`` — NumPy-compatible frontend on the TPU runtime.

Analog of the reference's ``python/mxnet/numpy/`` package (deep NumPy,
v>=1.6): true NumPy semantics (zero-dim arrays, boolean masks, NumPy
broadcasting/signatures) over the same registry/autograd/engine stack
as the classic ``mx.nd`` frontend. See multiarray.py for the array
type, ops.py for the ``_npi_*`` internal operators, linalg.py and
random.py for the sub-namespaces."""
from __future__ import annotations

import numpy as _onp

from .multiarray import *  # noqa: F401,F403
from .multiarray import __all__ as _ma_all
from . import linalg  # noqa: F401
from . import random  # noqa: F401

# dtype aliases (numpy interop: these ARE numpy dtypes, as in the
# reference where mx.np.float32 is numpy.float32)
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
bfloat16 = "bfloat16"
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
uint16 = _onp.uint16
uint32 = _onp.uint32
uint64 = _onp.uint64
bool_ = _onp.bool_
dtype = _onp.dtype

# constants
pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None
PZERO = 0.0
NZERO = -0.0

__all__ = list(_ma_all) + [
    "linalg", "random", "float16", "float32", "float64", "bfloat16",
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "bool_", "dtype", "pi", "e", "euler_gamma", "inf", "nan",
    "newaxis",
]
