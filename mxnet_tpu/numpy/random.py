"""``mx.np.random`` — NumPy-style sampling namespace.

Analog of the reference's python/mxnet/numpy/random.py. Scalar-parameter
draws dispatch the classic ``random_*`` registry ops (same threefry key
chain / kRandom resource analog); distributions the classic family
lacks dispatch the ``_npi_random_*`` ops. ``size=None`` returns a
0-dim array, per NumPy."""
from __future__ import annotations

from .. import random as _base_random
from .multiarray import _np_invoke, _proc, asarray

__all__ = ["seed", "uniform", "normal", "randn", "rand", "randint",
           "choice", "shuffle", "permutation", "gamma", "beta",
           "exponential", "chisquare", "lognormal", "laplace", "logistic",
           "gumbel", "pareto", "power", "rayleigh", "weibull",
           "multinomial", "poisson"]


def seed(seed_state):
    _base_random.seed(seed_state)


def _sz(size):
    return size


def uniform(low=0.0, high=1.0, size=None, dtype="float32"):
    return _np_invoke("random_uniform", [],
                      {"low": low, "high": high, "shape": size,
                       "dtype": dtype})


def normal(loc=0.0, scale=1.0, size=None, dtype="float32"):
    return _np_invoke("random_normal", [],
                      {"loc": loc, "scale": scale, "shape": size,
                       "dtype": dtype})


def randn(*size):
    return normal(0.0, 1.0, size or None)


def rand(*size):
    return uniform(0.0, 1.0, size or None)


def randint(low, high=None, size=None, dtype="int32"):
    if high is None:
        low, high = 0, low
    return _np_invoke("random_randint", [],
                      {"low": low, "high": high, "shape": size,
                       "dtype": dtype})


def choice(a, size=None, replace=True, p=None):
    inputs = [_proc(a) if not isinstance(a, int) else asarray(list(range(a)))]
    if p is not None:
        inputs.append(_proc(p))  # rides as the second tensor input
    return _np_invoke("_npi_random_choice", inputs,
                      {"size": size, "replace": replace})


def shuffle(x):
    """In-place permutation along the first axis (numpy semantics)."""
    out = _np_invoke("shuffle", [_proc(x)])
    x._set_data(out._data)


def permutation(x):
    if isinstance(x, int):
        x = asarray(list(range(x)))
    return _np_invoke("_npi_random_permutation", [_proc(x)])


def gamma(shape, scale=1.0, size=None, dtype="float32"):
    return _np_invoke("random_gamma", [],
                      {"alpha": shape, "beta": scale, "shape": size,
                       "dtype": dtype})


def beta(a, b, size=None):
    return _np_invoke("_npi_random_beta", [], {"a": a, "b": b, "size": size})


def exponential(scale=1.0, size=None):
    return _np_invoke("random_exponential", [],
                      {"lam": 1.0 / scale, "shape": size})


def chisquare(df, size=None):
    return _np_invoke("_npi_random_chisquare", [], {"df": df, "size": size})


def lognormal(mean=0.0, sigma=1.0, size=None):
    return _np_invoke("_npi_random_lognormal", [],
                      {"mean": mean, "sigma": sigma, "size": size})


def laplace(loc=0.0, scale=1.0, size=None):
    return _np_invoke("_npi_random_laplace", [],
                      {"loc": loc, "scale": scale, "size": size})


def logistic(loc=0.0, scale=1.0, size=None):
    return _np_invoke("_npi_random_logistic", [],
                      {"loc": loc, "scale": scale, "size": size})


def gumbel(loc=0.0, scale=1.0, size=None):
    return _np_invoke("_npi_random_gumbel", [],
                      {"loc": loc, "scale": scale, "size": size})


def pareto(a, size=None):
    return _np_invoke("_npi_random_pareto", [], {"a": a, "size": size})


def power(a, size=None):
    return _np_invoke("_npi_random_power", [], {"a": a, "size": size})


def rayleigh(scale=1.0, size=None):
    return _np_invoke("_npi_random_rayleigh", [],
                      {"scale": scale, "size": size})


def weibull(a, size=None):
    return _np_invoke("_npi_random_weibull", [], {"a": a, "size": size})


def multinomial(n, pvals, size=None):
    """Counts over len(pvals) outcomes — composed from the registry's
    sample_multinomial + one_hot (one dispatch per op, any size)."""
    import numpy as onp

    k = len(pvals)
    if size is None:
        reps, out_shape = 1, (k,)
    elif isinstance(size, int):
        reps, out_shape = size, (size, k)
    else:
        reps = int(onp.prod(size))
        out_shape = tuple(size) + (k,)
    probs = asarray([list(map(float, pvals))])
    draws = _np_invoke("sample_multinomial", [probs],
                       {"shape": (reps * int(n),)})
    oh = _np_invoke("one_hot", [draws.reshape(reps, int(n))], {"depth": k})
    return oh.sum(axis=1).astype("int64").reshape(out_shape)


def poisson(lam=1.0, size=None):
    return _np_invoke("random_poisson", [], {"lam": lam, "shape": size})
