"""``mx.np.linalg`` — NumPy linear-algebra namespace.

Analog of the reference's python/mxnet/numpy/linalg.py (backed by
src/operator/numpy/linalg/*.cc there; backed by the ``_npi_*`` linalg
registry ops here, which lower to XLA's decomposition custom calls —
the MXU-friendly path on TPU). The classic ``mx.nd.linalg_*`` ops
(potrf/gemm/trmm/...) remain the BLAS-style surface; this namespace is
the NumPy-style one."""
from __future__ import annotations

from .multiarray import _np_invoke, _proc

__all__ = ["norm", "svd", "inv", "pinv", "det", "slogdet", "eigh",
           "eigvalsh", "qr", "cholesky", "solve", "lstsq", "matrix_power",
           "matrix_rank", "multi_dot"]


def norm(x, ord=None, axis=None, keepdims=False):  # noqa: A002
    return _np_invoke("_npi_norm", [_proc(x)],
                      {"ord": ord, "axis": axis, "keepdims": keepdims})


def svd(a, full_matrices=False):
    return tuple(_np_invoke("_npi_svd", [_proc(a)],
                            {"full_matrices": full_matrices}))


def inv(a):
    return _np_invoke("_npi_inv", [_proc(a)])


def pinv(a, rcond=1e-15):
    return _np_invoke("_npi_pinv", [_proc(a)], {"rcond": rcond})


def det(a):
    return _np_invoke("_npi_det", [_proc(a)])


def slogdet(a):
    return tuple(_np_invoke("_npi_slogdet", [_proc(a)]))


def eigh(a, UPLO="L"):
    return tuple(_np_invoke("_npi_eigh", [_proc(a)], {"UPLO": UPLO}))


def eigvalsh(a, UPLO="L"):
    return _np_invoke("_npi_eigvalsh", [_proc(a)], {"UPLO": UPLO})


def qr(a, mode="reduced"):
    return tuple(_np_invoke("_npi_qr", [_proc(a)], {"mode": mode}))


def cholesky(a):
    return _np_invoke("_npi_cholesky", [_proc(a)])


def solve(a, b):
    return _np_invoke("_npi_solve", [_proc(a), _proc(b)])


def lstsq(a, b, rcond=None):
    return tuple(_np_invoke("_npi_lstsq", [_proc(a), _proc(b)],
                            {"rcond": rcond}))


def matrix_power(a, n):
    return _np_invoke("_npi_matrix_power", [_proc(a)], {"n": n})


def matrix_rank(a, tol=None):
    return _np_invoke("_npi_matrix_rank", [_proc(a)], {"tol": tol})


def multi_dot(arrays):
    return _np_invoke("_npi_multi_dot", [_proc(a) for a in arrays])
