"""NumPy-semantics internal operators (the ``_npi_*`` family).

The reference's deep-NumPy frontend (python/mxnet/numpy/multiarray.py,
v>=1.6) is backed by internal registry ops named ``_npi_*``
(src/operator/numpy/np_*.cc). Here the same contract holds: every
``mx.np.*`` function that is not expressible through an existing
classic op dispatches one of these registered ops, so the autograd
tape, AMP cast hook, profiler, symbolic tracing and the recorded
op-coverage gate all see np-mode work exactly like classic-mode work.

Only numpy-specific semantics get new entries; where a classic op is
already the right kernel (tanh, sum, clip, ...) ``mx.np`` reuses it —
the registry is the single source of compute either way.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.register import register_op
# newer jax exports the x64 context manager at top level; older jax
# keeps it in jax.experimental
from ..ops.pallas._util import _enable_x64 as _enable_x64_ctx

__all__ = []  # everything here is reached through the registry


# ---------------------------------------------------------------------------
# elementwise binaries numpy adds over the classic broadcast_* family
# ---------------------------------------------------------------------------
@register_op("_npi_floor_divide")
def _npi_floor_divide(a, b):
    return jnp.floor_divide(a, b)


@register_op("_npi_logaddexp")
def _npi_logaddexp(a, b):
    return jnp.logaddexp(a, b)


@register_op("_npi_logaddexp2")
def _npi_logaddexp2(a, b):
    return jnp.logaddexp2(a, b)


@register_op("_npi_copysign")
def _npi_copysign(a, b):
    return jnp.copysign(a, b)


@register_op("_npi_fmax")
def _npi_fmax(a, b):
    return jnp.fmax(a, b)


@register_op("_npi_fmin")
def _npi_fmin(a, b):
    return jnp.fmin(a, b)


@register_op("_npi_fmod")
def _npi_fmod(a, b):
    return jnp.fmod(a, b)


@register_op("_npi_bitwise_and", differentiable=False)
def _npi_bitwise_and(a, b):
    return jnp.bitwise_and(a, b)


@register_op("_npi_bitwise_or", differentiable=False)
def _npi_bitwise_or(a, b):
    return jnp.bitwise_or(a, b)


@register_op("_npi_bitwise_xor", differentiable=False)
def _npi_bitwise_xor(a, b):
    return jnp.bitwise_xor(a, b)


@register_op("_npi_invert", differentiable=False)
def _npi_invert(a):
    return jnp.invert(a)


@register_op("_npi_left_shift", differentiable=False)
def _npi_left_shift(a, b):
    return jnp.left_shift(a, b)


@register_op("_npi_right_shift", differentiable=False)
def _npi_right_shift(a, b):
    return jnp.right_shift(a, b)


@register_op("_npi_gcd", differentiable=False)
def _npi_gcd(a, b):
    return jnp.gcd(a, b)


@register_op("_npi_lcm", differentiable=False)
def _npi_lcm(a, b):
    return jnp.lcm(a, b)


@register_op("_npi_exp2")
def _npi_exp2(a):
    return jnp.exp2(a)


@register_op("_npi_nan_to_num")
def _npi_nan_to_num(a, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf)


@register_op("_npi_isclose", differentiable=False)
def _npi_isclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("_npi_signbit", differentiable=False)
def _npi_signbit(a):
    return jnp.signbit(a)


@register_op("_npi_heaviside")
def _npi_heaviside(a, b):
    return jnp.heaviside(a, b)


@register_op("_npi_ldexp")
def _npi_ldexp(a, b):
    return jnp.ldexp(a, b)


# ---------------------------------------------------------------------------
# reductions / statistics
# ---------------------------------------------------------------------------
@register_op("_npi_all", differentiable=False)
def _npi_all(a, axis=None, keepdims=False):
    return jnp.all(a, axis=axis, keepdims=keepdims)


@register_op("_npi_any", differentiable=False)
def _npi_any(a, axis=None, keepdims=False):
    return jnp.any(a, axis=axis, keepdims=keepdims)


@register_op("_npi_std")
def _npi_std(a, axis=None, ddof=0, keepdims=False):
    return jnp.std(a, axis=axis, ddof=ddof, keepdims=keepdims)


@register_op("_npi_var")
def _npi_var(a, axis=None, ddof=0, keepdims=False):
    return jnp.var(a, axis=axis, ddof=ddof, keepdims=keepdims)


@register_op("_npi_median")
def _npi_median(a, axis=None, keepdims=False):
    return jnp.median(a, axis=axis, keepdims=keepdims)


@register_op("_npi_quantile")
def _npi_quantile(a, q, axis=None, keepdims=False, interpolation="linear"):
    return jnp.quantile(a, q, axis=axis, keepdims=keepdims,
                        method=interpolation)


@register_op("_npi_percentile")
def _npi_percentile(a, q, axis=None, keepdims=False, interpolation="linear"):
    return jnp.percentile(a, q, axis=axis, keepdims=keepdims,
                          method=interpolation)


@register_op("_npi_average")
def _npi_average(a, weights=None, axis=None):
    return jnp.average(a, axis=axis, weights=weights)


@register_op("_npi_cumprod")
def _npi_cumprod(a, axis=None, dtype=None):
    return jnp.cumprod(a, axis=axis, dtype=dtype)


@register_op("_npi_count_nonzero", differentiable=False)
def _npi_count_nonzero(a, axis=None, keepdims=False):
    return jnp.count_nonzero(a, axis=axis, keepdims=keepdims)


@register_op("_npi_diff")
def _npi_diff(a, n=1, axis=-1):
    return jnp.diff(a, n=n, axis=axis)


@register_op("_npi_ptp")
def _npi_ptp(a, axis=None, keepdims=False):
    return jnp.ptp(a, axis=axis, keepdims=keepdims)


@register_op("_npi_bincount", differentiable=False)
def _npi_bincount(x, weights=None, minlength=0):
    # eager dispatch: concrete shapes, so the true length is known
    length = max(int(minlength), int(x.size and int(jnp.max(x)) + 1))
    return jnp.bincount(x, weights=weights, minlength=minlength,
                        length=length)


@register_op("_npi_histogram", differentiable=False)
def _npi_histogram(a, bins=10, range=None):
    return jnp.histogram(a, bins=bins, range=range)


@register_op("_npi_nanmax")
def _npi_nanmax(a, axis=None, keepdims=False):
    return jnp.nanmax(a, axis=axis, keepdims=keepdims)


@register_op("_npi_nanmin")
def _npi_nanmin(a, axis=None, keepdims=False):
    return jnp.nanmin(a, axis=axis, keepdims=keepdims)


@register_op("_npi_nanmean")
def _npi_nanmean(a, axis=None, keepdims=False):
    return jnp.nanmean(a, axis=axis, keepdims=keepdims)


# ---------------------------------------------------------------------------
# shape / rearrangement numpy-isms
# ---------------------------------------------------------------------------
@register_op("_npi_roll")
def _npi_roll(a, shift=1, axis=None):
    return jnp.roll(a, shift, axis=axis)


@register_op("_npi_rot90")
def _npi_rot90(a, k=1, axes=(0, 1)):
    return jnp.rot90(a, k=k, axes=tuple(axes))


@register_op("_npi_moveaxis")
def _npi_moveaxis(a, source=0, destination=0):
    return jnp.moveaxis(a, source, destination)


@register_op("_npi_tril")
def _npi_tril(a, k=0):
    return jnp.tril(a, k=k)


@register_op("_npi_triu")
def _npi_triu(a, k=0):
    return jnp.triu(a, k=k)


@register_op("_npi_trace")
def _npi_trace(a, offset=0, axis1=0, axis2=1):
    return jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2)


@register_op("_npi_diagonal")
def _npi_diagonal(a, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2)


@register_op("_npi_diagflat")
def _npi_diagflat(a, k=0):
    return jnp.diagflat(a, k=k)


@register_op("_npi_unique", differentiable=False)
def _npi_unique(a, return_index=False, return_inverse=False,
                return_counts=False):
    return jnp.unique(a, return_index=return_index,
                      return_inverse=return_inverse,
                      return_counts=return_counts)


@register_op("_npi_nonzero", differentiable=False)
def _npi_nonzero(a):
    # MXNet's np.nonzero returns a transposed-index matrix from the
    # internal op; the frontend unstacks it into the numpy tuple form
    return jnp.stack(jnp.nonzero(a), axis=0)


@register_op("_npi_flatnonzero", differentiable=False)
def _npi_flatnonzero(a):
    return jnp.flatnonzero(a)


@register_op("_npi_searchsorted", differentiable=False)
def _npi_searchsorted(a, v, side="left"):
    return jnp.searchsorted(a, v, side=side)


@register_op("_npi_take_along_axis")
def _npi_take_along_axis(a, indices, axis=-1):
    return jnp.take_along_axis(a, indices, axis=axis)


@register_op("_npi_pad")
def _npi_pad(a, pad_width=0, mode="constant", constant_values=0):
    pw = pad_width
    if isinstance(pw, (list, tuple)):
        pw = tuple(tuple(p) if isinstance(p, (list, tuple)) else p for p in pw)
    kw = {"constant_values": constant_values} if mode == "constant" else {}
    return jnp.pad(a, pw, mode=mode, **kw)


@register_op("_npi_append")
def _npi_append(a, b, axis=None):
    return jnp.append(a, b, axis=axis)


@register_op("_npi_interp")
def _npi_interp(x, xp, fp, left=None, right=None):
    return jnp.interp(x, xp, fp, left=left, right=right)


@register_op("_npi_where")
def _npi_where(cond, x, y):
    return jnp.where(cond, x, y)


@register_op("_npi_ediff1d")
def _npi_ediff1d(a):
    return jnp.ediff1d(a)


@register_op("_npi_cross")
def _npi_cross(a, b, axis=-1):
    return jnp.cross(a, b, axis=axis)


@register_op("_npi_kron")
def _npi_kron(a, b):
    return jnp.kron(a, b)


# ---------------------------------------------------------------------------
# products / contractions
# ---------------------------------------------------------------------------
@register_op("_npi_tensordot")
def _npi_tensordot(a, b, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(x) if isinstance(x, (list, tuple)) else x
                     for x in axes)
    return jnp.tensordot(a, b, axes=axes)


@register_op("_npi_einsum")
def _npi_einsum(*operands, subscripts="", optimize=True):
    return jnp.einsum(subscripts, *operands, optimize=bool(optimize))


@register_op("_npi_inner")
def _npi_inner(a, b):
    return jnp.inner(a, b)


@register_op("_npi_outer")
def _npi_outer(a, b):
    return jnp.outer(a, b)


@register_op("_npi_vdot")
def _npi_vdot(a, b):
    return jnp.vdot(a, b)


@register_op("_npi_matmul")
def _npi_matmul(a, b):
    return jnp.matmul(a, b)


@register_op("_npi_dot")
def _npi_dot(a, b):
    # numpy dot semantics (2D matmul, 1D inner, scalar mul) — distinct
    # from the classic mx.nd.dot which has transpose_a/b flags
    return jnp.dot(a, b)


# ---------------------------------------------------------------------------
# np.linalg
# ---------------------------------------------------------------------------
def _x64_safe(fn):
    """Scope out x64 for 32-bit inputs of SVD-based decompositions:
    with jax_enable_x64 on (base.py enables it for int64 NDArray
    parity), jnp.linalg's svd/pinv/lstsq emit f64-tainted graphs that
    abort the TPU compiler (TransposeFolding null-buffer check on this
    libtpu). Disabling x64 in-scope restores the pure-f32 graph; 64-bit
    inputs keep x64 so their numerics are untouched."""
    import functools

    @functools.wraps(fn)
    def wrapped(a, *rest, **kw):
        if hasattr(a, "dtype") and a.dtype.itemsize <= 4:
            with _enable_x64_ctx(False):
                return fn(a, *rest, **kw)
        return fn(a, *rest, **kw)

    return wrapped


@register_op("_npi_svd", num_visible_outputs=3)
@_x64_safe
def _npi_svd(a, full_matrices=False):
    u, s, vh = jnp.linalg.svd(a, full_matrices=full_matrices)
    return u, s, vh


@register_op("_npi_inv")
def _npi_inv(a):
    return jnp.linalg.inv(a)


@register_op("_npi_pinv")
@_x64_safe
def _npi_pinv(a, rcond=1e-15):
    return jnp.linalg.pinv(a, rtol=rcond)


@register_op("_npi_det")
def _npi_det(a):
    return jnp.linalg.det(a)


@register_op("_npi_slogdet", num_visible_outputs=2)
def _npi_slogdet(a):
    sign, logdet = jnp.linalg.slogdet(a)
    return sign, logdet


@register_op("_npi_eigh", num_visible_outputs=2)
def _npi_eigh(a, UPLO="L"):
    w, v = jnp.linalg.eigh(a, UPLO=UPLO)
    return w, v


@register_op("_npi_eigvalsh")
def _npi_eigvalsh(a, UPLO="L"):
    return jnp.linalg.eigvalsh(a, UPLO=UPLO)


@register_op("_npi_qr", num_visible_outputs=2)
def _npi_qr(a, mode="reduced"):
    q, r = jnp.linalg.qr(a, mode=mode)
    return q, r


@register_op("_npi_cholesky")
def _npi_cholesky(a):
    return jnp.linalg.cholesky(a)


@register_op("_npi_solve")
def _npi_solve(a, b):
    return jnp.linalg.solve(a, b)


@register_op("_npi_lstsq", differentiable=False, num_visible_outputs=4)
@_x64_safe
def _npi_lstsq(a, b, rcond=None):
    x, resid, rank, s = jnp.linalg.lstsq(a, b, rcond=rcond)
    return x, resid, rank, s


@register_op("_npi_matrix_power")
def _npi_matrix_power(a, n=1):
    return jnp.linalg.matrix_power(a, n)


@register_op("_npi_multi_dot")
def _npi_multi_dot(*arrays):
    return jnp.linalg.multi_dot(list(arrays))


@register_op("_npi_norm")
def _npi_norm(a, ord=None, axis=None, keepdims=False):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return jnp.linalg.norm(a, ord=ord, axis=axis, keepdims=keepdims)


@register_op("_npi_matrix_rank", differentiable=False)
@_x64_safe
def _npi_matrix_rank(a, tol=None):
    return jnp.linalg.matrix_rank(a, rtol=tol)


# ---------------------------------------------------------------------------
# np.random distributions beyond the classic random_* family
# (reference src/operator/numpy/random/np_*_op.cc). Key discipline is
# the shared threefry chain (mxnet_tpu/random.py) — same resource the
# classic sample ops draw from.
# ---------------------------------------------------------------------------
from .. import random as _random_mod  # noqa: E402


def _rkey(k):
    return _random_mod._next_key() if k is None else k


def _rshape(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


@register_op("_npi_random_beta", differentiable=False)
def _npi_random_beta(a=1.0, b=1.0, size=None, _rng_key=None):
    return jax.random.beta(_rkey(_rng_key), a, b, _rshape(size))


@register_op("_npi_random_chisquare", differentiable=False)
def _npi_random_chisquare(df=1.0, size=None, _rng_key=None):
    return jax.random.chisquare(_rkey(_rng_key), df, shape=_rshape(size))


@register_op("_npi_random_lognormal", differentiable=False)
def _npi_random_lognormal(mean=0.0, sigma=1.0, size=None, _rng_key=None):
    return jnp.exp(mean + sigma * jax.random.normal(_rkey(_rng_key),
                                                    _rshape(size)))


@register_op("_npi_random_laplace", differentiable=False)
def _npi_random_laplace(loc=0.0, scale=1.0, size=None, _rng_key=None):
    return loc + scale * jax.random.laplace(_rkey(_rng_key), _rshape(size))


@register_op("_npi_random_logistic", differentiable=False)
def _npi_random_logistic(loc=0.0, scale=1.0, size=None, _rng_key=None):
    return loc + scale * jax.random.logistic(_rkey(_rng_key), _rshape(size))


@register_op("_npi_random_gumbel", differentiable=False)
def _npi_random_gumbel(loc=0.0, scale=1.0, size=None, _rng_key=None):
    return loc + scale * jax.random.gumbel(_rkey(_rng_key), _rshape(size))


@register_op("_npi_random_pareto", differentiable=False)
def _npi_random_pareto(a=1.0, size=None, _rng_key=None):
    return jax.random.pareto(_rkey(_rng_key), a, shape=_rshape(size)) - 1.0


@register_op("_npi_random_rayleigh", differentiable=False)
def _npi_random_rayleigh(scale=1.0, size=None, _rng_key=None):
    return jax.random.rayleigh(_rkey(_rng_key), scale, shape=_rshape(size))


@register_op("_npi_random_weibull", differentiable=False)
def _npi_random_weibull(a=1.0, size=None, _rng_key=None):
    u = jax.random.uniform(_rkey(_rng_key), _rshape(size), minval=1e-7,
                           maxval=1.0)
    return (-jnp.log(u)) ** (1.0 / a)


@register_op("_npi_random_power", differentiable=False)
def _npi_random_power(a=1.0, size=None, _rng_key=None):
    u = jax.random.uniform(_rkey(_rng_key), _rshape(size), minval=1e-7,
                           maxval=1.0)
    return u ** (1.0 / a)


@register_op("_npi_random_choice", differentiable=False)
def _npi_random_choice(a, p=None, size=None, replace=True, _rng_key=None):
    # p is the optional SECOND tensor input (invoke passes tensor
    # inputs positionally), so it precedes the keyword params
    return jax.random.choice(_rkey(_rng_key), a, _rshape(size),
                             replace=replace, p=p)


@register_op("_npi_random_permutation", differentiable=False)
def _npi_random_permutation(x, _rng_key=None):
    return jax.random.permutation(_rkey(_rng_key), x)


# ---------------------------------------------------------------------------
# bool-dtype comparisons/logicals (numpy returns bool; the classic
# broadcast_* family returns the input dtype per MXNet convention —
# reference np_elemwise_broadcast_logic_op.cc)
# ---------------------------------------------------------------------------
_NP_CMP = {
    "_npi_equal": jnp.equal,
    "_npi_not_equal": jnp.not_equal,
    "_npi_greater": jnp.greater,
    "_npi_greater_equal": jnp.greater_equal,
    "_npi_less": jnp.less,
    "_npi_less_equal": jnp.less_equal,
    "_npi_logical_and": jnp.logical_and,
    "_npi_logical_or": jnp.logical_or,
    "_npi_logical_xor": jnp.logical_xor,
}
for _name, _fn in _NP_CMP.items():
    register_op(_name, differentiable=False)(_fn)


@register_op("_npi_logical_not", differentiable=False)
def _npi_logical_not(a):
    return jnp.logical_not(a)


@register_op("_npi_broadcast_to")
def _npi_broadcast_to(a, shape=()):
    # numpy broadcast_to prepends axes; the classic broadcast_to op
    # keeps MXNet's same-rank/0-keeps-dim contract
    return jnp.broadcast_to(a, tuple(shape))


@register_op("_npi_argwhere", differentiable=False)
def _npi_argwhere(a):
    return jnp.argwhere(a)


# ----------------------------------------------------------------------
# composed-function ops (round 5): the eager frontend builds these in
# Python; registering jnp-backed single ops gives `mx.sym.np` a static
# graph lowering too (upstream symbol/numpy has backend ops for the
# same reason). Multi-output counts are parameter-inferable, so the
# symbolic layer exposes real output selectors.
# ----------------------------------------------------------------------
@register_op("_npi_vstack")
def _npi_vstack(*arrays):
    return jnp.vstack(arrays)


@register_op("_npi_hstack")
def _npi_hstack(*arrays):
    return jnp.hstack(arrays)


@register_op("_npi_dstack")
def _npi_dstack(*arrays):
    return jnp.dstack(arrays)


@register_op("_npi_column_stack")
def _npi_column_stack(*arrays):
    return jnp.column_stack(arrays)


def _split_count(params):
    ios = params.get("indices_or_sections", 1)
    if isinstance(ios, (list, tuple)):
        return len(ios) + 1
    return int(ios)


@register_op("_npi_split_np", wrap=False, infer_num_outputs=_split_count)
def _npi_split_np(x, indices_or_sections=1, axis=0):
    ios = indices_or_sections
    return tuple(jnp.split(x, ios if isinstance(ios, int) else list(ios),
                           axis=int(axis)))


@register_op("_npi_array_split", wrap=False, infer_num_outputs=_split_count)
def _npi_array_split(x, indices_or_sections=1, axis=0):
    ios = indices_or_sections
    return tuple(jnp.array_split(
        x, ios if isinstance(ios, int) else list(ios), axis=int(axis)))


@register_op("_npi_meshgrid", wrap=False,
             infer_num_outputs=lambda p: int(p.get("num_outputs", 1)))
def _npi_meshgrid(*arrays, indexing="xy", num_outputs=None):
    return tuple(jnp.meshgrid(*arrays, indexing=indexing))


@register_op("_npi_broadcast_arrays", wrap=False,
             infer_num_outputs=lambda p: int(p.get("num_outputs", 1)))
def _npi_broadcast_arrays(*arrays, num_outputs=None):
    return tuple(jnp.broadcast_arrays(*arrays))


@register_op("_npi_atleast_1d")
def _npi_atleast_1d(a):
    return jnp.atleast_1d(a)


@register_op("_npi_atleast_2d")
def _npi_atleast_2d(a):
    return jnp.atleast_2d(a)


@register_op("_npi_atleast_3d")
def _npi_atleast_3d(a):
    return jnp.atleast_3d(a)


@register_op("_npi_around")
def _npi_around(a, decimals=0):
    return jnp.round(a, int(decimals))
