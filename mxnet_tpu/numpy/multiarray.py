"""``mx.np`` — the NumPy-compatible array frontend (deep NumPy).

Analog of the reference's ``python/mxnet/numpy/multiarray.py`` (v>=1.6):
an :class:`ndarray` with true NumPy semantics — zero-dim arrays, boolean
masking, NumPy operator/broadcasting rules, NumPy function signatures —
living on the same imperative runtime as the classic ``mx.nd`` frontend.

Every function here dispatches a registered operator (classic ops where
the kernel already exists, ``_npi_*`` ops from .ops otherwise), so
autograd recording, AMP casts, the profiler, hybridization traces and
the op-coverage gate treat np-mode exactly like classic mode. Arrays
convert losslessly both ways via ``as_np_ndarray``/``as_nd_ndarray``
(zero-copy; tape-linked under autograd.record).
"""
from __future__ import annotations

import numpy as onp
import jax.numpy as jnp

from ..base import dtype_np
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray, _wrap
from ..ndarray.register import get_op, invoke
from . import ops as _ops  # registers the _npi_* family  # noqa: F401

__all__ = [
    "ndarray", "array", "asarray", "zeros", "ones", "empty", "full",
    "zeros_like", "ones_like", "full_like", "empty_like", "arange",
    "linspace", "logspace", "eye", "identity", "meshgrid", "tril", "triu",
    "diag", "diagflat", "diagonal", "trace", "copy",
    # manipulation
    "reshape", "ravel", "transpose", "moveaxis", "swapaxes", "concatenate",
    "stack", "vstack", "hstack", "dstack", "column_stack", "split",
    "array_split", "hsplit", "vsplit", "expand_dims", "squeeze",
    "broadcast_to", "broadcast_arrays", "tile", "repeat", "flip", "fliplr",
    "flipud", "roll", "rot90", "pad", "append", "where", "take",
    "take_along_axis", "clip", "nonzero", "flatnonzero", "unique", "sort",
    "argsort", "argmax", "argmin", "searchsorted", "atleast_1d",
    "atleast_2d", "atleast_3d", "insert_dims_like",
    # math
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "fmod", "power", "maximum", "minimum", "fmax",
    "fmin", "hypot", "arctan2", "logaddexp", "logaddexp2", "copysign",
    "ldexp", "heaviside", "gcd", "lcm", "bitwise_and", "bitwise_or",
    "bitwise_xor", "invert", "bitwise_not", "left_shift", "right_shift",
    "logical_and", "logical_or", "logical_xor", "logical_not", "equal",
    "not_equal", "greater", "greater_equal", "less", "less_equal",
    # reductions
    "sum", "prod", "mean", "std", "var", "median", "quantile", "percentile",
    "average", "min", "max", "amin", "amax", "nanmin", "nanmax", "nanmean",
    "nansum", "nanprod", "cumsum", "cumprod", "all", "any", "count_nonzero",
    "ptp", "diff", "bincount", "histogram", "around", "round", "round_",
    # contractions
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum",
    "kron", "cross",
    # logic / misc
    "isclose", "allclose", "array_equal", "interp", "ediff1d",
    "nan_to_num", "shape", "size", "ndim", "may_share_memory",
    "result_type", "promote_types", "finfo", "iinfo", "isnan", "isinf",
    "isfinite", "signbit",
]


def _np_invoke(name, inputs, params=None, out=None):
    """Dispatch a registry op, always wrapping outputs as mx.np.ndarray
    (mx.np functions return np arrays regardless of input flavor)."""
    return invoke(get_op(name), inputs, params, out=out, wrap_cls=ndarray)


def _proc(x, ctx=None):
    """Coerce a function argument to something invoke accepts, turning
    lists/numpy into arrays while leaving NDArray/scalars alone."""
    if isinstance(x, NDArray) or isinstance(x, (int, float, bool)):
        return x
    if x is None:
        return None
    return array(x, ctx=ctx)


# ---------------------------------------------------------------------------
# the ndarray type
# ---------------------------------------------------------------------------
class ndarray(NDArray):
    """NumPy-semantics array (mx.np.ndarray).

    Shares the NDArray runtime — engine vars, autograd tape, context
    placement — and differs only in API semantics (reference
    python/mxnet/numpy/multiarray.py: same handle type under a NumPy
    calling convention)."""

    __slots__ = ()

    def __repr__(self):
        a = self.asnumpy()
        body = onp.array2string(a, separator=", ")
        dt = f", dtype={self.dtype}" if self.dtype not in (onp.float32,) else ""
        ctx = "" if self._ctx.device_type == "cpu" else f", ctx={self._ctx}"
        return f"array({body}{dt}{ctx})"

    # -- conversion ----------------------------------------------------
    def as_np_ndarray(self):
        return self

    def tolist(self):
        return self.asnumpy().tolist()

    # -- numpy-signature overrides ------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = tuple(kwargs.get("shape", shape))
        return _np_invoke("reshape", [self], {"shape": shape})

    def flatten(self, order="C"):
        # numpy flatten = raveled copy (NOT the classic (N, -1) Flatten)
        return self.reshape(-1)

    def ravel(self, order="C"):
        return self.reshape(-1)

    def mean(self, axis=None, dtype=None, keepdims=False, **kw):
        r = _np_invoke("mean", [self], {"axis": axis, "keepdims": keepdims})
        return r.astype(dtype) if dtype is not None else r

    def std(self, axis=None, ddof=0, keepdims=False):
        return _np_invoke("_npi_std", [self],
                          {"axis": axis, "ddof": ddof, "keepdims": keepdims})

    def var(self, axis=None, ddof=0, keepdims=False):
        return _np_invoke("_npi_var", [self],
                          {"axis": axis, "ddof": ddof, "keepdims": keepdims})

    def all(self, axis=None, keepdims=False):
        return _np_invoke("_npi_all", [self],
                          {"axis": axis, "keepdims": keepdims})

    def any(self, axis=None, keepdims=False):
        return _np_invoke("_npi_any", [self],
                          {"axis": axis, "keepdims": keepdims})

    def cumsum(self, axis=None, dtype=None):
        r = _np_invoke("cumsum", [self], {"axis": axis})
        return r.astype(dtype) if dtype is not None else r

    def round(self, decimals=0):
        return around(self, decimals)

    def clip(self, min=None, max=None):  # noqa: A002
        return clip(self, min, max)

    def take(self, indices, axis=None, mode="clip"):
        return take(self, indices, axis=axis, mode=mode)

    def nonzero(self):
        return nonzero(self)

    def dot(self, b):
        return dot(self, b)

    def item(self, *args):
        a = self.asnumpy()
        return a.item(*args) if args else a.item()

    def argmax(self, axis=None):
        return _np_invoke("argmax", [self], {"axis": axis})

    def argmin(self, axis=None):
        return _np_invoke("argmin", [self], {"axis": axis})

    def sort(self, axis=-1):
        # in-place by numpy convention; routed through the registered
        # op so the engine/profiler/AMP see it like any other dispatch
        r = _np_invoke("sort", [self], {"axis": axis, "is_ascend": True})
        self._set_data(r._data)

    def argsort(self, axis=-1):
        return _np_invoke("argsort", [self], {"axis": axis, "is_ascend": True})

    def squeeze(self, axis=None):
        return _np_invoke("squeeze", [self], {"axis": axis})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _np_invoke("transpose", [self], {"axes": axes or None})

    def sum(self, axis=None, dtype=None, keepdims=False, **kw):
        r = _np_invoke("sum", [self], {"axis": axis, "keepdims": keepdims})
        return r.astype(dtype) if dtype is not None else r

    def prod(self, axis=None, keepdims=False):
        return _np_invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return _np_invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return _np_invoke("min", [self], {"axis": axis, "keepdims": keepdims})


# numpy comparison dunders: bool results (override the classic
# input-dtype-returning broadcast comparisons)
def _np_cmp_dunder(opname):
    def f(self, other):
        if other is None:
            return NotImplemented
        return _np_invoke(opname, [self, _proc(other)])
    return f


ndarray.__eq__ = _np_cmp_dunder("_npi_equal")
ndarray.__ne__ = _np_cmp_dunder("_npi_not_equal")
ndarray.__lt__ = _np_cmp_dunder("_npi_less")
ndarray.__le__ = _np_cmp_dunder("_npi_less_equal")
ndarray.__gt__ = _np_cmp_dunder("_npi_greater")
ndarray.__ge__ = _np_cmp_dunder("_npi_greater_equal")
ndarray.__and__ = _np_cmp_dunder("_npi_bitwise_and")
ndarray.__or__ = _np_cmp_dunder("_npi_bitwise_or")
ndarray.__xor__ = _np_cmp_dunder("_npi_bitwise_xor")
ndarray.__invert__ = lambda self: _np_invoke("_npi_invert", [self])
ndarray.__hash__ = lambda self: id(self)


# install as the np-mode wrap class for the whole runtime. NOTE: the
# ndarray PACKAGE self-aliases its `ndarray` attribute (mx.nd.ndarray
# is mx.nd), so target the defining module through sys.modules.
import sys as _sys  # noqa: E402

_sys.modules["mxnet_tpu.ndarray.ndarray"]._NP_CLS = ndarray


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------
def array(object, dtype=None, ctx=None):  # noqa: A002
    if isinstance(object, NDArray):
        data = object._data
        if dtype is not None:
            data = data.astype(dtype_np(dtype))
        if ctx is None:
            ctx = object._ctx  # inherit the source's placement
        elif ctx != object._ctx:
            import jax
            data = jax.device_put(data, ctx.jax_device)
        return ndarray(data, ctx)
    ctx = ctx or current_context()
    a = onp.asarray(object)
    if dtype is None:
        dtype = onp.float32 if a.dtype == onp.float64 else a.dtype
    import jax
    return ndarray(jax.device_put(jnp.asarray(a, dtype=dtype_np(dtype)),
                                  ctx.jax_device), ctx)


def asarray(a, dtype=None, ctx=None):
    if isinstance(a, ndarray) and dtype is None and ctx is None:
        return a
    return array(a, dtype=dtype, ctx=ctx)


def _creation(fill):
    def f(shape, dtype=None, ctx=None, fill_value=None):
        ctx = ctx or current_context()
        dt = dtype_np(dtype or "float32")
        if isinstance(shape, int):
            shape = (shape,)
        val = fill if fill_value is None else fill_value
        return ndarray(jnp.full(tuple(shape), val, dtype=dt), ctx)
    return f


def zeros(shape, dtype=None, ctx=None):
    return _creation(0.0)(shape, dtype, ctx)


def ones(shape, dtype=None, ctx=None):
    return _creation(1.0)(shape, dtype, ctx)


def empty(shape, dtype=None, ctx=None):
    return _creation(0.0)(shape, dtype, ctx)


def full(shape, fill_value, dtype=None, ctx=None):
    if dtype is None and isinstance(fill_value, (int, bool)) \
            and not isinstance(fill_value, float):
        dtype = onp.asarray(fill_value).dtype
    return _creation(None)(shape, dtype, ctx, fill_value=fill_value)


def zeros_like(a, dtype=None):
    r = _np_invoke("zeros_like", [_proc(a)])
    return r.astype(dtype) if dtype is not None else r


def ones_like(a, dtype=None):
    r = _np_invoke("ones_like", [_proc(a)])
    return r.astype(dtype) if dtype is not None else r


def full_like(a, fill_value, dtype=None):
    r = _np_invoke("_full_like", [_proc(a)], {"value": fill_value})
    return r.astype(dtype) if dtype is not None else r


def empty_like(a, dtype=None):
    return zeros_like(a, dtype)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    ctx = ctx or current_context()
    out = jnp.arange(start, stop, step, dtype and dtype_np(dtype))
    if out.dtype == jnp.float64:
        out = out.astype(jnp.float32)
    return ndarray(out, ctx)


def _f32_default(arr):
    # x64 is enabled package-wide (int64 NDArray parity), so jnp float
    # defaults land on f64 — the frontend's default float is f32
    return arr.astype(jnp.float32) if arr.dtype == jnp.float64 else arr


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None):
    ctx = ctx or current_context()
    r = jnp.linspace(start, stop, num, endpoint=endpoint, retstep=retstep,
                     dtype=dtype and dtype_np(dtype), axis=axis)
    if retstep:
        return ndarray(_f32_default(r[0]), ctx), float(r[1])
    return ndarray(_f32_default(r), ctx)


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             ctx=None):
    ctx = ctx or current_context()
    return ndarray(_f32_default(
        jnp.logspace(start, stop, num, endpoint=endpoint, base=base,
                     dtype=dtype and dtype_np(dtype))), ctx)


def eye(N, M=None, k=0, dtype="float32", ctx=None):
    ctx = ctx or current_context()
    return ndarray(jnp.eye(N, M, k=k, dtype=dtype_np(dtype)), ctx)


def identity(n, dtype="float32", ctx=None):
    return eye(n, dtype=dtype, ctx=ctx)


def meshgrid(*xi, indexing="xy"):
    """Composed from registry ops (reshape + broadcast_to) so autograd
    flows — no dedicated kernel needed."""
    xs = [asarray(x) for x in xi]
    n = len(xs)
    if n == 1:
        return [xs[0].reshape(-1)]
    lens = [int(x.size) for x in xs]
    # axis each input varies along in the output grid
    pos = list(range(n))
    if indexing == "xy":
        pos[0], pos[1] = 1, 0
    dims = [0] * n
    for i, p in enumerate(pos):
        dims[p] = lens[i]
    outs = []
    for i, x in enumerate(xs):
        shp = [1] * n
        shp[pos[i]] = -1
        g = x.reshape(-1).reshape(tuple(shp))
        outs.append(broadcast_to(g, tuple(dims)))
    return outs


def tril(a, k=0):
    return _np_invoke("_npi_tril", [_proc(a)], {"k": k})


def triu(a, k=0):
    return _np_invoke("_npi_triu", [_proc(a)], {"k": k})


def diag(v, k=0):
    return _np_invoke("diag", [_proc(v)], {"k": k})


def diagflat(v, k=0):
    return _np_invoke("_npi_diagflat", [_proc(v)], {"k": k})


def diagonal(a, offset=0, axis1=0, axis2=1):
    return _np_invoke("_npi_diagonal", [_proc(a)],
                      {"offset": offset, "axis1": axis1, "axis2": axis2})


def trace(a, offset=0, axis1=0, axis2=1):
    return _np_invoke("_npi_trace", [_proc(a)],
                      {"offset": offset, "axis1": axis1, "axis2": axis2})


def copy(a):
    return _np_arg(a).copy()


# ---------------------------------------------------------------------------
# manipulation
# ---------------------------------------------------------------------------
def _np_arg(x):
    """Coerce to mx.np.ndarray so method-delegating functions keep the
    always-np output contract even for classic-NDArray inputs."""
    if isinstance(x, ndarray):
        return x
    if isinstance(x, NDArray):
        return x.as_np_ndarray()
    return array(x)


def reshape(a, newshape, order="C"):
    return _np_arg(a).reshape(newshape)


def ravel(a, order="C"):
    return _np_arg(a).reshape(-1)


def transpose(a, axes=None):
    return _np_arg(a).transpose(*(axes or ()))


def moveaxis(a, source, destination):
    return _np_invoke("_npi_moveaxis", [_proc(a)],
                      {"source": source, "destination": destination})


def swapaxes(a, axis1, axis2):
    return _np_invoke("swapaxes", [_proc(a)], {"dim1": axis1, "dim2": axis2})


def concatenate(seq, axis=0, out=None):
    arrs = [_proc(a) for a in seq]
    if axis is None:
        arrs = [a.reshape(-1) for a in arrs]
        axis = 0
    return _np_invoke("concat", arrs, {"dim": axis}, out=out)


def stack(arrays, axis=0, out=None):
    return _np_invoke("stack", [_proc(a) for a in arrays], {"axis": axis},
                      out=out)


def vstack(tup):
    arrs = [atleast_2d(a) for a in tup]
    return concatenate(arrs, axis=0)


def hstack(tup):
    arrs = [atleast_1d(a) for a in tup]
    if arrs and arrs[0].ndim == 1:
        return concatenate(arrs, axis=0)
    return concatenate(arrs, axis=1)


def dstack(tup):
    arrs = [atleast_3d(a) for a in tup]
    return concatenate(arrs, axis=2)


def column_stack(tup):
    arrs = []
    for a in tup:
        a = atleast_1d(a)
        if a.ndim < 2:
            a = a.reshape(-1, 1)
        arrs.append(a)
    return concatenate(arrs, axis=1)


def _split_points(n, indices_or_sections, even_required):
    if isinstance(indices_or_sections, int):
        k = indices_or_sections
        if even_required and n % k != 0:
            raise ValueError("array split does not result in an equal division")
        base, extra = divmod(n, k)
        pts, acc = [], 0
        for i in range(k - 1):
            acc += base + (1 if i < extra else 0)
            pts.append(acc)
        return pts
    return list(indices_or_sections)


def _split_impl(a, indices_or_sections, axis, even_required):
    a = _proc(a)
    n = a.shape[axis]
    pts = [0] + _split_points(n, indices_or_sections, even_required) + [n]
    outs = []
    for b, e in zip(pts[:-1], pts[1:]):
        outs.append(_np_invoke("slice_axis", [a],
                               {"axis": axis, "begin": b, "end": e}))
    return outs


def split(ary, indices_or_sections, axis=0):
    return _split_impl(ary, indices_or_sections, axis, even_required=True)


def array_split(ary, indices_or_sections, axis=0):
    return _split_impl(ary, indices_or_sections, axis, even_required=False)


def hsplit(ary, indices_or_sections):
    a = _proc(ary)
    return _split_impl(a, indices_or_sections, 0 if a.ndim == 1 else 1, True)


def vsplit(ary, indices_or_sections):
    return _split_impl(ary, indices_or_sections, 0, True)


def expand_dims(a, axis):
    return _np_invoke("expand_dims", [_proc(a)], {"axis": axis})


def squeeze(a, axis=None):
    return _np_invoke("squeeze", [_proc(a)], {"axis": axis})


def broadcast_to(array, shape):  # noqa: A002
    return _np_invoke("_npi_broadcast_to", [_proc(array)],
                      {"shape": tuple(shape) if not isinstance(shape, int)
                       else (shape,)})


def broadcast_arrays(*args):
    arrs = [_proc(a) for a in args]
    target = onp.broadcast_shapes(*[a.shape for a in arrs])
    return [broadcast_to(a, target) for a in arrs]


def tile(a, reps):
    return _np_invoke("tile", [_proc(a)], {"reps": reps})


def repeat(a, repeats, axis=None):
    return _np_invoke("repeat", [_proc(a)], {"repeats": repeats, "axis": axis})


def flip(m, axis=None):
    a = _proc(m)
    if axis is None:
        axis = tuple(range(a.ndim))
    return _np_invoke("flip", [a], {"axis": axis})


def fliplr(m):
    return flip(m, 1)


def flipud(m):
    return flip(m, 0)


def roll(a, shift, axis=None):
    return _np_invoke("_npi_roll", [_proc(a)], {"shift": shift, "axis": axis})


def rot90(m, k=1, axes=(0, 1)):
    return _np_invoke("_npi_rot90", [_proc(m)], {"k": k, "axes": tuple(axes)})


def pad(array, pad_width, mode="constant", constant_values=0):  # noqa: A002
    return _np_invoke("_npi_pad", [_proc(array)],
                      {"pad_width": pad_width, "mode": mode,
                       "constant_values": constant_values})


def append(arr, values, axis=None):
    return _np_invoke("_npi_append", [_proc(arr), _proc(values)],
                      {"axis": axis})


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition)
    return _np_invoke("_npi_where", [_proc(condition), _proc(x), _proc(y)])


def take(a, indices, axis=None, mode="clip", out=None):
    a = _proc(a)
    if axis is None:
        a = a.reshape(-1)
        axis = 0
    return _np_invoke("take", [a, _proc(indices)],
                      {"axis": axis, "mode": mode}, out=out)


def take_along_axis(arr, indices, axis):
    return _np_invoke("_npi_take_along_axis", [_proc(arr), _proc(indices)],
                      {"axis": axis})


def clip(a, a_min=None, a_max=None, out=None):
    if a_min is None and a_max is None:
        raise ValueError("One of a_min and a_max must be given")
    a = _proc(a)
    if a_min is None:
        return minimum(a, a_max) if out is None else \
            _np_invoke("broadcast_minimum", [a, _proc(a_max)], out=out)
    if a_max is None:
        return maximum(a, a_min) if out is None else \
            _np_invoke("broadcast_maximum", [a, _proc(a_min)], out=out)
    return _np_invoke("clip", [a], {"a_min": a_min, "a_max": a_max}, out=out)


def nonzero(a):
    mat = _np_invoke("_npi_nonzero", [_proc(a)])
    return tuple(_np_invoke("_slice_get", [mat], {"key": i})
                 for i in range(mat.shape[0]))


def flatnonzero(a):
    return _np_invoke("_npi_flatnonzero", [_proc(a)])


def unique(ar, return_index=False, return_inverse=False, return_counts=False):
    r = _np_invoke("_npi_unique", [_proc(ar)],
                   {"return_index": return_index,
                    "return_inverse": return_inverse,
                    "return_counts": return_counts})
    return tuple(r) if isinstance(r, list) else r


def sort(a, axis=-1):
    return _np_invoke("sort", [_proc(a)], {"axis": axis, "is_ascend": True})


def argsort(a, axis=-1):
    return _np_invoke("argsort", [_proc(a)], {"axis": axis, "is_ascend": True})


def argmax(a, axis=None, out=None):
    return _np_invoke("argmax", [_proc(a)], {"axis": axis}, out=out)


def argmin(a, axis=None, out=None):
    return _np_invoke("argmin", [_proc(a)], {"axis": axis}, out=out)


def searchsorted(a, v, side="left"):
    return _np_invoke("_npi_searchsorted", [_proc(a), _proc(v)],
                      {"side": side})


def atleast_1d(*arys):
    res = []
    for a in arys:
        a = _proc(a)
        if not isinstance(a, NDArray):
            a = array(a)
        res.append(a.reshape(1) if a.ndim == 0 else a)
    return res[0] if len(res) == 1 else res


def atleast_2d(*arys):
    res = []
    for a in arys:
        a = _proc(a)
        if not isinstance(a, NDArray):
            a = array(a)
        if a.ndim == 0:
            a = a.reshape(1, 1)
        elif a.ndim == 1:
            a = expand_dims(a, 0)
        res.append(a)
    return res[0] if len(res) == 1 else res


def atleast_3d(*arys):
    res = []
    for a in arys:
        a = _proc(a)
        if not isinstance(a, NDArray):
            a = array(a)
        if a.ndim == 0:
            a = a.reshape(1, 1, 1)
        elif a.ndim == 1:
            a = a.reshape(1, -1, 1)
        elif a.ndim == 2:
            a = expand_dims(a, 2)
        res.append(a)
    return res[0] if len(res) == 1 else res


def insert_dims_like(a, like):
    """Convenience (not in numpy): right-pad ``a``'s shape with 1s to
    match ``like``'s rank for broadcasting."""
    a = _proc(a)
    while a.ndim < _proc(like).ndim:
        a = expand_dims(a, -1)
    return a


# ---------------------------------------------------------------------------
# elementwise math — factories
# ---------------------------------------------------------------------------
def _make_binary(fname, opname):
    def f(x1, x2, out=None):
        return _np_invoke(opname, [_proc(x1), _proc(x2)], None, out=out)
    f.__name__ = fname
    f.__doc__ = f"numpy.{fname} semantics; dispatches registry op {opname}."
    return f


_BINARY_TABLE = {
    "add": "broadcast_add", "subtract": "broadcast_sub",
    "multiply": "broadcast_mul", "divide": "broadcast_div",
    "true_divide": "broadcast_div", "mod": "broadcast_mod",
    "remainder": "broadcast_mod", "fmod": "_npi_fmod",
    "power": "broadcast_power", "maximum": "broadcast_maximum",
    "minimum": "broadcast_minimum", "fmax": "_npi_fmax",
    "fmin": "_npi_fmin", "hypot": "broadcast_hypot", "arctan2": "arctan2",
    "logaddexp": "_npi_logaddexp", "logaddexp2": "_npi_logaddexp2",
    "copysign": "_npi_copysign", "ldexp": "_npi_ldexp",
    "heaviside": "_npi_heaviside", "gcd": "_npi_gcd", "lcm": "_npi_lcm",
    "bitwise_and": "_npi_bitwise_and", "bitwise_or": "_npi_bitwise_or",
    "bitwise_xor": "_npi_bitwise_xor", "left_shift": "_npi_left_shift",
    "right_shift": "_npi_right_shift",
    # numpy comparisons/logicals return bool (the classic broadcast_*
    # family returns the input dtype, MXNet convention)
    "logical_and": "_npi_logical_and",
    "logical_or": "_npi_logical_or",
    "logical_xor": "_npi_logical_xor",
    "equal": "_npi_equal", "not_equal": "_npi_not_equal",
    "greater": "_npi_greater", "greater_equal": "_npi_greater_equal",
    "less": "_npi_less", "less_equal": "_npi_less_equal",
    "floor_divide": "_npi_floor_divide",
}

for _f, _o in _BINARY_TABLE.items():
    globals()[_f] = _make_binary(_f, _o)


def _make_unary(fname, opname):
    def f(x, out=None):
        return _np_invoke(opname, [_proc(x)], None, out=out)
    f.__name__ = fname
    f.__doc__ = f"numpy.{fname} semantics; dispatches registry op {opname}."
    return f


_UNARY_TABLE = {
    "absolute": "abs", "abs": "abs", "fabs": "abs", "sign": "sign",
    "exp": "exp", "expm1": "expm1", "exp2": "_npi_exp2", "log": "log",
    "log2": "log2", "log10": "log10", "log1p": "log1p", "sqrt": "sqrt",
    "cbrt": "cbrt", "square": "square", "reciprocal": "reciprocal",
    "negative": "negative", "positive": "copy", "sin": "sin", "cos": "cos",
    "tan": "tan", "arcsin": "arcsin", "arccos": "arccos",
    "arctan": "arctan", "sinh": "sinh", "cosh": "cosh", "tanh": "tanh",
    "arcsinh": "arcsinh", "arccosh": "arccosh", "arctanh": "arctanh",
    "degrees": "degrees", "radians": "radians", "deg2rad": "radians",
    "rad2deg": "degrees", "rint": "rint", "floor": "floor", "ceil": "ceil",
    "trunc": "trunc", "fix": "fix", "isnan": "isnan", "isinf": "isinf",
    "isfinite": "isfinite", "logical_not": "_npi_logical_not",
    "invert": "_npi_invert", "bitwise_not": "_npi_invert",
    "signbit": "_npi_signbit",
}

for _f, _o in _UNARY_TABLE.items():
    globals()[_f] = _make_unary(_f, _o)

__all__ += [f for f in (*_UNARY_TABLE, *_BINARY_TABLE) if f not in __all__]


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return _np_invoke("_npi_nan_to_num", [_proc(x)],
                      {"nan": nan, "posinf": posinf, "neginf": neginf})


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------
def sum(a, axis=None, dtype=None, keepdims=False, out=None):  # noqa: A001
    r = _np_invoke("sum", [_proc(a)], {"axis": axis, "keepdims": keepdims},
                   out=out)
    return r.astype(dtype) if dtype is not None else r


def prod(a, axis=None, keepdims=False, out=None):
    return _np_invoke("prod", [_proc(a)], {"axis": axis, "keepdims": keepdims},
                      out=out)


def mean(a, axis=None, dtype=None, keepdims=False, out=None):
    r = _np_invoke("mean", [_proc(a)], {"axis": axis, "keepdims": keepdims},
                   out=out)
    return r.astype(dtype) if dtype is not None else r


def std(a, axis=None, ddof=0, keepdims=False):
    return _np_invoke("_npi_std", [_proc(a)],
                      {"axis": axis, "ddof": ddof, "keepdims": keepdims})


def var(a, axis=None, ddof=0, keepdims=False):
    return _np_invoke("_npi_var", [_proc(a)],
                      {"axis": axis, "ddof": ddof, "keepdims": keepdims})


def median(a, axis=None, keepdims=False):
    return _np_invoke("_npi_median", [_proc(a)],
                      {"axis": axis, "keepdims": keepdims})


def quantile(a, q, axis=None, keepdims=False, interpolation="linear"):
    return _np_invoke("_npi_quantile", [_proc(a), _proc(q)],
                      {"axis": axis, "keepdims": keepdims,
                       "interpolation": interpolation})


def percentile(a, q, axis=None, keepdims=False, interpolation="linear"):
    return _np_invoke("_npi_percentile", [_proc(a), _proc(q)],
                      {"axis": axis, "keepdims": keepdims,
                       "interpolation": interpolation})


def average(a, axis=None, weights=None):
    inputs = [_proc(a)]
    if weights is not None:
        inputs.append(_proc(weights))
    return _np_invoke("_npi_average", inputs, {"axis": axis})


def max(a, axis=None, keepdims=False, out=None):  # noqa: A001
    return _np_invoke("max", [_proc(a)], {"axis": axis, "keepdims": keepdims},
                      out=out)


def min(a, axis=None, keepdims=False, out=None):  # noqa: A001
    return _np_invoke("min", [_proc(a)], {"axis": axis, "keepdims": keepdims},
                      out=out)


amax = max
amin = min


def nanmax(a, axis=None, keepdims=False):
    return _np_invoke("_npi_nanmax", [_proc(a)],
                      {"axis": axis, "keepdims": keepdims})


def nanmin(a, axis=None, keepdims=False):
    return _np_invoke("_npi_nanmin", [_proc(a)],
                      {"axis": axis, "keepdims": keepdims})


def nanmean(a, axis=None, keepdims=False):
    return _np_invoke("_npi_nanmean", [_proc(a)],
                      {"axis": axis, "keepdims": keepdims})


def nansum(a, axis=None, keepdims=False):
    return _np_invoke("nansum", [_proc(a)], {"axis": axis, "keepdims": keepdims})


def nanprod(a, axis=None, keepdims=False):
    return _np_invoke("nanprod", [_proc(a)], {"axis": axis, "keepdims": keepdims})


def cumsum(a, axis=None, dtype=None):
    r = _np_invoke("cumsum", [_proc(a)], {"axis": axis})
    return r.astype(dtype) if dtype is not None else r


def cumprod(a, axis=None, dtype=None):
    return _np_invoke("_npi_cumprod", [_proc(a)],
                      {"axis": axis, "dtype": dtype})


def all(a, axis=None, keepdims=False):  # noqa: A001
    return _np_invoke("_npi_all", [_proc(a)],
                      {"axis": axis, "keepdims": keepdims})


def any(a, axis=None, keepdims=False):  # noqa: A001
    return _np_invoke("_npi_any", [_proc(a)],
                      {"axis": axis, "keepdims": keepdims})


def count_nonzero(a, axis=None, keepdims=False):
    return _np_invoke("_npi_count_nonzero", [_proc(a)],
                      {"axis": axis, "keepdims": keepdims})


def ptp(a, axis=None, keepdims=False):
    return _np_invoke("_npi_ptp", [_proc(a)],
                      {"axis": axis, "keepdims": keepdims})


def diff(a, n=1, axis=-1):
    return _np_invoke("_npi_diff", [_proc(a)], {"n": n, "axis": axis})


def ediff1d(ary):
    return _np_invoke("_npi_ediff1d", [_proc(ary)])


def bincount(x, weights=None, minlength=0):
    inputs = [_proc(x)]
    if weights is not None:
        inputs.append(_proc(weights))
    return _np_invoke("_npi_bincount", inputs, {"minlength": minlength})


def histogram(a, bins=10, range=None):  # noqa: A002
    r = _np_invoke("_npi_histogram", [_proc(a)],
                   {"bins": bins, "range": range})
    return r[0], r[1]


def around(a, decimals=0, out=None):
    if decimals == 0:
        return _np_invoke("round", [_proc(a)], None, out=out)
    f = 10.0 ** decimals
    r = _np_invoke("round", [multiply(_proc(a), f)]) / f
    if out is not None:
        out._set_data(r._data)
        return out
    return r


round = around  # noqa: A001
round_ = around


# ---------------------------------------------------------------------------
# contractions
# ---------------------------------------------------------------------------
def dot(a, b, out=None):
    return _np_invoke("_npi_dot", [_proc(a), _proc(b)], None, out=out)


def vdot(a, b):
    return _np_invoke("_npi_vdot", [_proc(a), _proc(b)])


def inner(a, b):
    return _np_invoke("_npi_inner", [_proc(a), _proc(b)])


def outer(a, b):
    return _np_invoke("_npi_outer", [_proc(a), _proc(b)])


def matmul(a, b, out=None):
    return _np_invoke("_npi_matmul", [_proc(a), _proc(b)], None, out=out)


def tensordot(a, b, axes=2):
    return _np_invoke("_npi_tensordot", [_proc(a), _proc(b)], {"axes": axes})


def einsum(subscripts, *operands, optimize=True):
    return _np_invoke("_npi_einsum", [_proc(o) for o in operands],
                      {"subscripts": subscripts, "optimize": optimize})


def kron(a, b):
    return _np_invoke("_npi_kron", [_proc(a), _proc(b)])


def cross(a, b, axis=-1):
    return _np_invoke("_npi_cross", [_proc(a), _proc(b)], {"axis": axis})


# ---------------------------------------------------------------------------
# logic / misc
# ---------------------------------------------------------------------------
def isclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    return _np_invoke("_npi_isclose", [_proc(a), _proc(b)],
                      {"rtol": rtol, "atol": atol, "equal_nan": equal_nan})


def allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    return bool(isclose(a, b, rtol, atol, equal_nan).all().item())


def array_equal(a1, a2):
    a1, a2 = _proc(a1), _proc(a2)
    if a1.shape != a2.shape:
        return False
    return bool(equal(a1, a2).all().item())


def interp(x, xp, fp, left=None, right=None):
    return _np_invoke("_npi_interp", [_proc(x), _proc(xp), _proc(fp)],
                      {"left": left, "right": right})


def shape(a):
    return _proc(a).shape


def size(a):
    return _proc(a).size


def ndim(a):
    return _proc(a).ndim


def may_share_memory(a, b):
    return False  # buffers are immutable jax arrays; writes rebind


def result_type(*args):
    return onp.result_type(*[
        a.dtype if isinstance(a, NDArray) else a for a in args])


def promote_types(t1, t2):
    return onp.promote_types(t1, t2)


def finfo(dtype):
    return onp.finfo(onp.dtype(dtype_np(dtype)))


def iinfo(dtype):
    return onp.iinfo(onp.dtype(dtype_np(dtype)))


# ---------------------------------------------------------------------------
# tail: index helpers, window functions, remaining creation fns
# ---------------------------------------------------------------------------
def argwhere(a):
    return _np_invoke("_npi_argwhere", [_proc(a)])


def dsplit(ary, indices_or_sections):
    a = _proc(ary)
    if a.ndim < 3:
        raise ValueError("dsplit only works on arrays of 3 or more "
                         "dimensions")
    return _split_impl(a, indices_or_sections, 2, even_required=True)


def tri(N, M=None, k=0, dtype="float32", ctx=None):
    ctx = ctx or current_context()
    return ndarray(jnp.tri(N, M, k, dtype=dtype_np(dtype)), ctx)


def vander(x, N=None, increasing=False):
    a = asarray(x)
    if a.ndim != 1:
        raise ValueError("x must be a one-dimensional array or sequence")
    n = int(a.size) if N is None else int(N)
    # cumulative multiplies (numpy uses multiply.accumulate): integer
    # powers stay EXACT, unlike the exp/log pow lowering, and the
    # construction stays differentiable through the registry ops
    cols = [ones_like(a)]
    for _ in range(1, n):
        cols.append(multiply(cols[-1], a))
    if not increasing:
        cols = cols[::-1]
    return stack(cols, axis=1)


def hanning(M, ctx=None):
    ctx = ctx or current_context()
    return ndarray(jnp.hanning(int(M)).astype(jnp.float32), ctx)


def hamming(M, ctx=None):
    ctx = ctx or current_context()
    return ndarray(jnp.hamming(int(M)).astype(jnp.float32), ctx)


def blackman(M, ctx=None):
    ctx = ctx or current_context()
    return ndarray(jnp.blackman(int(M)).astype(jnp.float32), ctx)


def indices(dimensions, dtype="int32", ctx=None):
    ctx = ctx or current_context()
    return ndarray(jnp.indices(tuple(dimensions),
                               dtype=dtype_np(dtype)), ctx)


def row_stack(arrays):
    return vstack(arrays)


def rollaxis(a, axis, start=0):
    x = _proc(a)
    return ndarray(jnp.rollaxis(x._data, int(axis), int(start)), x.ctx)


def delete(arr, obj, axis=None):
    x = _proc(arr)
    if isinstance(obj, NDArray):
        obj = onp.asarray(obj.asnumpy())
    return ndarray(jnp.delete(x._data, obj, axis=axis), x.ctx)


def insert(arr, obj, values, axis=None):
    x = _proc(arr)
    v = _proc(values)
    vdata = v._data if isinstance(v, NDArray) else v
    if isinstance(obj, NDArray):
        obj = onp.asarray(obj.asnumpy())
    return ndarray(jnp.insert(x._data, obj, vdata, axis=axis), x.ctx)


def diag_indices_from(arr):
    x = _proc(arr)
    if x.ndim < 2:
        raise ValueError("input array must be at least 2-d")
    if len(set(x.shape)) != 1:
        raise ValueError("All dimensions of input must be of equal length")
    idx = jnp.arange(x.shape[0])
    return tuple(ndarray(idx, x.ctx) for _ in range(x.ndim))


def unravel_index(indices, shape):
    i = _proc(indices)
    raw = i._data if isinstance(i, NDArray) else onp.asarray(i)
    ctx = i.ctx if isinstance(i, NDArray) else current_context()
    return tuple(ndarray(c, ctx) for c in
                 jnp.unravel_index(raw, tuple(int(s) for s in shape)))


def _copy_out(res, out):
    if out is None:
        return res
    out[:] = res
    return out


def isposinf(x, out=None):
    a = _proc(x)
    return _copy_out(logical_and(isinf(a), greater(a, 0.0)), out)


def isneginf(x, out=None):
    a = _proc(x)
    return _copy_out(logical_and(isinf(a), less(a, 0.0)), out)


def float_power(x1, x2):
    # numpy semantics: promote to the widest float BEFORE the power —
    # stays on registry ops so gradients flow. Python scalars promote
    # too (2**-1 on raw ints raises in jax).
    a, b = _proc(x1), _proc(x2)
    a = a.astype("float64") if isinstance(a, NDArray) else float(a)
    b = b.astype("float64") if isinstance(b, NDArray) else float(b)
    if not isinstance(a, NDArray) and not isinstance(b, NDArray):
        a = array(a, dtype="float64")
    return power(a, b)


def polyval(p, x):
    # Horner's scheme over registry ops: differentiable in both p and x
    c = _proc(p)
    v = _proc(x)
    if not isinstance(c, NDArray) or c.ndim != 1:
        raise ValueError("p must be a 1-D array of coefficients")
    if int(c.size) == 0:
        return zeros_like(v)  # numpy: empty coefficients -> 0
    out = zeros_like(v) + c[0]
    for i in range(1, int(c.size)):
        out = add(multiply(out, v), c[i])
    return out


def tril_indices(n, k=0, m=None, ctx=None):
    ctx = ctx or current_context()
    r, c = jnp.tril_indices(n, k, m)
    return ndarray(r, ctx), ndarray(c, ctx)


def triu_indices(n, k=0, m=None, ctx=None):
    ctx = ctx or current_context()
    r, c = jnp.triu_indices(n, k, m)
    return ndarray(r, ctx), ndarray(c, ctx)


__all__ += ["argwhere", "dsplit", "tri", "vander", "hanning", "hamming",
            "blackman", "indices", "tril_indices", "triu_indices",
            "row_stack", "rollaxis", "delete", "insert",
            "diag_indices_from", "unravel_index", "isposinf", "isneginf",
            "float_power", "polyval"]
