"""mx.sym.sparse namespace (storage-type-aware symbolic ops).

Symbolically everything is dense under XLA; these exist for API parity
with python/mxnet/symbol/sparse.py."""
from __future__ import annotations

from .symbol import _make_node
from ..ndarray.register import get_op


def dot(lhs, rhs, transpose_a=False, transpose_b=False, name=None):
    return _make_node(get_op("dot"), [lhs, rhs],
                      {"transpose_a": transpose_a, "transpose_b": transpose_b},
                      name=name)


def retain(data, indices, name=None):
    return _make_node(get_op("take"), [data, indices], {"axis": 0}, name=name)
