from .symbol import (
    Symbol, Variable, var, Group, load, load_json, zeros, ones, arange,
)
from . import symbol as _symbol_mod
import sys as _sys

# op namespace codegen (mirrors mx.sym.<op>)
from .symbol import _populate_symbol_ops

_populate_symbol_ops(_sys.modules[__name__])

# sub-namespaces for parity
from . import random  # noqa: E402
from . import linalg  # noqa: E402
from . import sparse  # noqa: E402
from . import contrib  # noqa: E402
