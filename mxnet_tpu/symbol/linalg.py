"""mx.sym.linalg namespace."""
from __future__ import annotations

from .symbol import _make_node
from ..ndarray.register import get_op


def gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, name=None):
    return _make_node(get_op("linalg_gemm"), [A, B, C],
                      {"transpose_a": transpose_a, "transpose_b": transpose_b,
                       "alpha": alpha, "beta": beta}, name=name)


def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, name=None):
    return _make_node(get_op("linalg_gemm2"), [A, B],
                      {"transpose_a": transpose_a, "transpose_b": transpose_b,
                       "alpha": alpha}, name=name)


def potrf(A, name=None):
    return _make_node(get_op("linalg_potrf"), [A], {}, name=name)
